"""Unit tests for obs/device_telemetry.py: the poller's degradation
contract (None/raising memory_stats — the CPU tier-1 backend), headroom
derivation and the one-shot low-HBM warning episode, the memory-ledger
math against a fake sharded param tree + CacheEngine sizing, and the
swap-byte accounting."""
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import pytest

import intellillm_tpu.obs.device_telemetry as dt_mod
from intellillm_tpu.obs.device_telemetry import DeviceTelemetry


class _FakeDev:
    def __init__(self, platform, dev_id, stats):
        self.platform = platform
        self.id = dev_id
        self._stats = stats

    def memory_stats(self):
        if isinstance(self._stats, Exception):
            raise self._stats
        return self._stats


def _telemetry(**kw):
    kw.setdefault("enabled", True)
    kw.setdefault("poll_s", 60.0)
    kw.setdefault("headroom_warn", 0.05)
    return DeviceTelemetry(**kw)


def test_poll_samples_every_device_and_derives_min_headroom(monkeypatch):
    devs = [
        _FakeDev("tpu", 0, {"bytes_in_use": 600, "bytes_limit": 1000,
                            "peak_bytes_in_use": 800}),
        _FakeDev("tpu", 1, {"bytes_in_use": 900, "bytes_limit": 1000,
                            "peak_bytes_in_use": 950}),
    ]
    monkeypatch.setattr(jax, "local_devices", lambda: devs)
    t = _telemetry()
    sample = t.poll_once()
    assert sample["tpu:0"] == {"bytes_in_use": 600, "bytes_limit": 1000,
                               "peak_bytes": 800}
    assert sample["tpu:1"]["peak_bytes"] == 950
    # min over devices: tpu:1 is the constrained one.
    assert t.headroom_ratio() == pytest.approx(0.1)
    snap = t.snapshot()
    assert snap["headroom_ratio"] == pytest.approx(0.1)
    assert snap["last_poll_age_s"] is not None


def test_poll_degrades_on_none_and_raising_memory_stats(monkeypatch):
    devs = [_FakeDev("cpu", 0, None),
            _FakeDev("cpu", 1, RuntimeError("not supported"))]
    monkeypatch.setattr(jax, "local_devices", lambda: devs)
    t = _telemetry()
    sample = t.poll_once()
    assert set(sample) == {"cpu:0", "cpu:1"}
    for entry in sample.values():
        assert entry == {"bytes_in_use": None, "bytes_limit": None,
                         "peak_bytes": None}
    assert t.headroom_ratio() is None
    assert t.snapshot()["low_hbm"] is False
    if t._metrics is not None:
        # The exported gauge must be NaN, not a 0.0 that would read as
        # "out of HBM" and trip low-headroom alert rules.
        import math
        assert math.isnan(t._metrics.gauge_headroom._value.get())


def test_poll_survives_real_cpu_backend():
    """On the tier-1 CPU backend memory_stats() returns None — the poller
    must still emit one entry per device and never raise."""
    t = _telemetry()
    sample = t.poll_once()
    assert len(sample) == len(jax.local_devices())
    for label, entry in sample.items():
        assert label.startswith("cpu:")
        assert set(entry) == {"bytes_in_use", "bytes_limit", "peak_bytes"}


def test_low_hbm_warning_is_one_shot_per_episode(monkeypatch):
    low = {"bytes_in_use": 990, "bytes_limit": 1000,
           "peak_bytes_in_use": 990}
    high = {"bytes_in_use": 100, "bytes_limit": 1000,
            "peak_bytes_in_use": 990}
    dev = _FakeDev("tpu", 0, low)
    monkeypatch.setattr(jax, "local_devices", lambda: [dev])
    warnings = []
    monkeypatch.setattr(
        dt_mod.logger, "warning",
        lambda msg, *args: warnings.append(msg % args))
    t = _telemetry(headroom_warn=0.05)
    t.set_ledger({"params": 500, "kv_pool": 400}, log_table=False)

    t.poll_once()
    t.poll_once()  # still low: must NOT fire again
    assert len(warnings) == 1
    assert "LOW HBM HEADROOM" in warnings[0]
    assert t.snapshot()["low_hbm"] is True
    assert t.snapshot()["low_hbm_warnings"] == 1

    dev._stats = high
    t.poll_once()  # recovery clears the episode
    assert t.snapshot()["low_hbm"] is False

    dev._stats = low
    t.poll_once()  # new episode: fires once more
    assert len(warnings) == 2
    assert t.snapshot()["low_hbm_warnings"] == 2


def test_residual_other_component_from_live_sample(monkeypatch):
    dev = _FakeDev("tpu", 0, {"bytes_in_use": 1000, "bytes_limit": 4000,
                              "peak_bytes_in_use": 1000})
    monkeypatch.setattr(jax, "local_devices", lambda: [dev])
    t = _telemetry()
    t.set_ledger({"params": 500, "kv_pool": 300, "cpu_swap_pool": 999},
                 log_table=False)
    t.poll_once()
    # other = in_use - (params + kv_pool); the host pool is not on-device.
    assert t.ledger()["other"] == 200

    dev._stats = {"bytes_in_use": 100, "bytes_limit": 4000,
                  "peak_bytes_in_use": 1000}
    t.poll_once()
    assert t.ledger()["other"] == 0  # clamped, never negative


def test_ledger_math_against_fake_param_tree_and_cache_sizing():
    """worker.memory_ledger(): params from the (shard-aware) param tree,
    kv_pool from CacheEngine physical block bytes x block count, swap
    pool from logical bytes x cpu block count."""
    from intellillm_tpu.parallel.mesh import param_shard_bytes
    from intellillm_tpu.worker.cache_engine import CacheEngine
    from intellillm_tpu.worker.worker import Worker

    params = {"wte": jnp.zeros((64, 32), jnp.float32),
              "layers": [{"w": jnp.zeros((32, 32), jnp.float32)},
                         {"w": jnp.zeros((32, 32), jnp.float32)}]}
    expected_params = (64 * 32 + 2 * 32 * 32) * 4
    assert param_shard_bytes(params) == expected_params

    model_config = SimpleNamespace(
        dtype="float32",
        get_head_size=lambda: 16,
        get_total_num_kv_heads=lambda: 4,
        get_num_layers=lambda: 2)
    w = Worker.__new__(Worker)
    w.params = params
    w.model_config = model_config
    w.parallel_config = SimpleNamespace(tensor_parallel_size=1)
    w.cache_config = SimpleNamespace(block_size=8, cache_dtype="auto",
                                     num_device_blocks=10, num_cpu_blocks=3)
    w.cache_engine = object()  # ledger only checks it exists

    ledger = w.memory_ledger()
    physical = CacheEngine.get_cache_block_size(
        8, "auto", model_config, w.parallel_config)
    logical = CacheEngine.get_logical_cache_block_size(
        8, "auto", model_config)
    assert ledger["params"] == expected_params
    assert ledger["kv_pool"] == physical * 10
    assert ledger["cpu_swap_pool"] == logical * 3
    # head_size 16 pads to the 128-lane tile on device: physical > logical.
    assert physical > logical


def test_swap_accounting_totals():
    t = _telemetry()
    t.record_swap("out", 4, 100)
    t.record_swap("out", 1, 100)
    t.record_swap("in", 2, 100)
    t.record_swap("copy", 3, 700)
    t.record_swap("in", 0, 100)  # zero blocks: no-op
    assert t.swap_bytes_total() == {"in": 200, "out": 500, "copy": 2100}
    assert t.snapshot()["swap_bytes_total"]["copy"] == 2100


def test_disabled_telemetry_is_inert(monkeypatch):
    monkeypatch.setenv("INTELLILLM_DEVICE_TELEMETRY", "0")
    t = DeviceTelemetry()  # enabled resolved from env
    assert t.enabled is False
    assert t.poll_once() == {}
    t.record_swap("in", 5, 100)
    assert t.swap_bytes_total() == {"in": 0, "out": 0, "copy": 0}
    t.set_ledger({"params": 1})
    assert t.ledger() == {}
    t.attach()  # must not start a poller thread
    assert t._poller is None
    assert t.snapshot()["enabled"] is False


def test_configure_and_env_defaults(monkeypatch):
    monkeypatch.setenv("INTELLILLM_DEVICE_POLL_S", "2.5")
    monkeypatch.setenv("INTELLILLM_HBM_HEADROOM_WARN", "0.2")
    t = DeviceTelemetry(enabled=True)
    assert t.poll_s == 2.5
    assert t.headroom_warn == 0.2
    t.configure(poll_s=7.0, headroom_warn=0.1)
    assert t.poll_s == 7.0
    assert t.headroom_warn == 0.1
    monkeypatch.setenv("INTELLILLM_DEVICE_POLL_S", "bogus")
    assert DeviceTelemetry(enabled=True).poll_s == 10.0  # fallback


def test_global_accessor_and_reset():
    t = dt_mod.get_device_telemetry()
    assert dt_mod.get_device_telemetry() is t
    t.record_swap("in", 1, 8)
    t.reset_for_testing()
    assert t.swap_bytes_total() == {"in": 0, "out": 0, "copy": 0}
    assert t._poller is None
