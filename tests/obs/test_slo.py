"""SLOTracker unit tests: trace-replay derivation, rolling-window
goodput math (including the exact-boundary case), percentile summary,
and the flight-recorder integration used by the engine finish path."""
import pytest

from intellillm_tpu.obs import get_flight_recorder
from intellillm_tpu.obs.slo import (SLOTracker, _percentile,
                                    derive_request_metrics)


def _ev(ts, event, detail=None):
    out = {"ts": ts, "event": event}
    if detail is not None:
        out["detail"] = detail
    return out


class TestDerive:

    def test_full_lifecycle(self):
        rec = derive_request_metrics([
            _ev(10.0, "arrived"),
            _ev(10.2, "queued"),
            _ev(11.2, "scheduled"),
            _ev(11.3, "prefill_start"),
            _ev(11.5, "first_token"),
            _ev(13.5, "finished", "stop"),
        ], num_generation_tokens=5)
        assert rec["queue_wait_s"] == pytest.approx(1.0)   # scheduled-queued
        assert rec["ttft_s"] == pytest.approx(1.5)         # first_token-arrived
        assert rec["tpot_s"] == pytest.approx(2.0 / 4)     # (fin-ft)/(gen-1)
        assert rec["e2e_s"] == pytest.approx(3.5)
        assert rec["reason"] == "stop"
        assert rec["preemptions"] == {}

    def test_queue_wait_excludes_tokenization(self):
        # 0.8s between arrived and queued is tokenization, not queue wait.
        rec = derive_request_metrics([
            _ev(0.0, "arrived"), _ev(0.8, "queued"),
            _ev(1.0, "scheduled"), _ev(1.1, "first_token"),
            _ev(2.0, "finished", "length"),
        ], num_generation_tokens=2)
        assert rec["queue_wait_s"] == pytest.approx(0.2)

    def test_preemption_counts_by_mode(self):
        rec = derive_request_metrics([
            _ev(0.0, "queued"), _ev(0.1, "scheduled"),
            _ev(0.2, "preempted", "recompute"),
            _ev(0.3, "preempted", "swap"),
            _ev(0.4, "preempted", "swap"),
            _ev(0.5, "first_token"),
            _ev(1.0, "finished", "stop"),
        ], num_generation_tokens=3)
        assert rec["preemptions"] == {"recompute": 1, "swap": 2}

    def test_aborted_while_queued(self):
        rec = derive_request_metrics([
            _ev(0.0, "arrived"), _ev(0.1, "queued"),
            _ev(5.1, "aborted"),
        ], num_generation_tokens=0)
        assert rec["reason"] == "abort"
        assert rec["ttft_s"] is None
        assert rec["tpot_s"] is None
        # Never scheduled: the whole life was queue wait.
        assert rec["queue_wait_s"] == pytest.approx(5.0)

    def test_unterminated_trace_is_none(self):
        assert derive_request_metrics(
            [_ev(0.0, "queued"), _ev(0.1, "scheduled")], 0) is None

    def test_single_token_request(self):
        rec = derive_request_metrics([
            _ev(0.0, "queued"), _ev(0.1, "first_token"),
            _ev(0.1, "finished", "length"),
        ], num_generation_tokens=1)
        assert rec["tpot_s"] == pytest.approx(0.0)

    def test_hops_partition_queued_to_terminal(self):
        rec = derive_request_metrics([
            _ev(10.0, "arrived"),
            _ev(10.2, "queued"),
            _ev(11.2, "scheduled"),
            _ev(11.5, "first_token"),
            _ev(13.5, "finished", "stop"),
        ], num_generation_tokens=5)
        hops = rec["hops"]
        assert hops["replica_queue"] == pytest.approx(1.0)
        assert hops["prefill"] == pytest.approx(0.3)
        assert hops["decode"] == pytest.approx(2.0)
        # The hops partition the span from `queued` to the terminal.
        assert sum(hops.values()) == pytest.approx(13.5 - 10.2)

    def test_hops_only_evidenced_spans(self):
        rec = derive_request_metrics([
            _ev(0.0, "queued"), _ev(5.0, "aborted"),
        ], num_generation_tokens=0)
        assert rec["hops"] == {}  # never scheduled: nothing to attribute

    def test_rerouted_is_terminal_with_reason(self):
        rec = derive_request_metrics([
            _ev(0.0, "arrived"), _ev(0.1, "queued"),
            _ev(0.2, "scheduled"), _ev(0.5, "first_token"),
            _ev(1.0, "rerouted", "replica=r0 died mid-stream"),
        ], num_generation_tokens=3)
        assert rec is not None
        assert rec["reason"] == "rerouted"
        assert rec["e2e_s"] == pytest.approx(1.0)


def _record(ttft_s, tpot_s, reason="stop", **kwargs):
    return {"queue_wait_s": kwargs.get("queue_wait_s", 0.01),
            "ttft_s": ttft_s, "tpot_s": tpot_s,
            "e2e_s": kwargs.get("e2e_s", 1.0),
            "generation_tokens": kwargs.get("generation_tokens", 8),
            "preemptions": kwargs.get("preemptions", {}),
            "reason": reason}


class TestGoodput:

    def test_exact_boundary_counts_as_good(self):
        t = SLOTracker(slo_ttft_ms=100.0, slo_tpot_ms=10.0)
        t.observe(_record(ttft_s=0.100, tpot_s=0.010))   # exactly at SLO
        t.observe(_record(ttft_s=0.1001, tpot_s=0.010))  # TTFT over
        t.observe(_record(ttft_s=0.100, tpot_s=0.0101))  # TPOT over
        t.observe(_record(ttft_s=0.050, tpot_s=0.005))   # well under
        assert t.summary()["goodput_ratio"] == pytest.approx(0.5)

    def test_no_first_token_excluded_from_goodput(self):
        t = SLOTracker(slo_ttft_ms=100.0, slo_tpot_ms=10.0)
        t.observe(_record(ttft_s=None, tpot_s=None, reason="abort"))
        s = t.summary()
        assert s["goodput_ratio"] is None
        assert s["window"] == 1
        assert s["finished_total"] == {"abort": 1}

    def test_single_token_judged_on_ttft_alone(self):
        t = SLOTracker(slo_ttft_ms=100.0, slo_tpot_ms=10.0)
        t.observe(_record(ttft_s=0.05, tpot_s=None))
        assert t.summary()["goodput_ratio"] == pytest.approx(1.0)

    def test_window_eviction_updates_goodput(self):
        t = SLOTracker(window=2, slo_ttft_ms=100.0, slo_tpot_ms=10.0)
        t.observe(_record(ttft_s=1.0, tpot_s=1.0))    # bad
        t.observe(_record(ttft_s=0.01, tpot_s=0.001))  # good
        assert t.summary()["goodput_ratio"] == pytest.approx(0.5)
        t.observe(_record(ttft_s=0.01, tpot_s=0.001))  # evicts the bad one
        assert t.summary()["goodput_ratio"] == pytest.approx(1.0)
        assert t.summary()["window"] == 2

    def test_configure_overrides_thresholds(self):
        t = SLOTracker(slo_ttft_ms=100.0, slo_tpot_ms=10.0)
        t.configure(slo_ttft_ms=500.0, slo_tpot_ms=50.0)
        t.observe(_record(ttft_s=0.3, tpot_s=0.03))
        assert t.summary()["goodput_ratio"] == pytest.approx(1.0)


class TestSummary:

    def test_percentile_nearest_rank(self):
        vals = sorted(float(v) for v in range(1, 101))
        assert _percentile(vals, 50) == 50.0
        assert _percentile(vals, 90) == 90.0
        assert _percentile(vals, 99) == 99.0
        assert _percentile([7.0], 99) == 7.0

    def test_summary_percentiles_ordered_and_ms(self):
        t = SLOTracker(slo_ttft_ms=1000.0, slo_tpot_ms=200.0)
        for i in range(1, 51):
            t.observe(_record(ttft_s=i / 1000.0, tpot_s=i / 10000.0,
                              queue_wait_s=i / 100.0))
        s = t.summary()
        for key in ("queue_wait_ms", "ttft_ms", "tpot_ms", "e2e_ms"):
            d = s[key]
            assert d["p50"] <= d["p90"] <= d["p99"]
        assert s["ttft_ms"]["p50"] == pytest.approx(25.0)
        assert s["queue_wait_ms"]["p99"] == pytest.approx(500.0)

    def test_empty_summary(self):
        t = SLOTracker()
        s = t.summary()
        assert s["window"] == 0
        assert s["goodput_ratio"] is None
        assert s["ttft_ms"] is None

    def test_preemption_totals_accumulate(self):
        t = SLOTracker()
        t.observe(_record(ttft_s=0.1, tpot_s=0.01,
                          preemptions={"swap": 2}))
        t.observe(_record(ttft_s=0.1, tpot_s=0.01,
                          preemptions={"swap": 1, "recompute": 1}))
        assert t.summary()["preemptions_total"] == {"swap": 3,
                                                    "recompute": 1}

    def test_hops_ms_percentiles(self):
        t = SLOTracker()
        for i in range(1, 11):
            rec = _record(ttft_s=0.01, tpot_s=0.001)
            rec["hops"] = {"prefill": i / 100.0, "decode": i / 10.0}
            t.observe(rec)
        s = t.summary()
        assert s["hops_ms"]["prefill"]["p50"] == pytest.approx(50.0)
        assert s["hops_ms"]["decode"]["p99"] == pytest.approx(1000.0)
        t2 = SLOTracker()
        assert t2.summary()["hops_ms"] is None

    def test_slowest_panel_bounded_and_sorted(self):
        t = SLOTracker(slo_ttft_ms=100.0, slo_tpot_ms=10.0)
        for i in range(1, 21):
            rec = _record(ttft_s=0.01, tpot_s=0.001, e2e_s=float(i))
            rec["request_id"] = f"req-{i}"
            rec["hops"] = {"decode": float(i) - 0.5}
            t.observe(rec)
        slowest = t.summary()["slowest"]
        assert len(slowest) == 8  # bounded keep
        assert [r["request_id"] for r in slowest] == [
            f"req-{i}" for i in range(20, 12, -1)]  # worst first
        assert slowest[0]["e2e_ms"] == pytest.approx(20000.0)
        assert slowest[0]["hops_ms"]["decode"] == pytest.approx(19500.0)
        assert slowest[0]["slo_violated"] is False

    def test_slo_violation_flagged_in_record(self):
        t = SLOTracker(slo_ttft_ms=100.0, slo_tpot_ms=10.0)
        bad = _record(ttft_s=5.0, tpot_s=1.0)
        bad["request_id"] = "slow-1"
        t.observe(bad)
        assert bad["slo_violated"] is True  # the trace sink's keep signal
        assert t.summary()["slowest"][0]["slo_violated"] is True

    def test_rerouted_excluded_from_goodput(self):
        t = SLOTracker(slo_ttft_ms=100.0, slo_tpot_ms=10.0)
        # A rerouted victim attempt that would FAIL SLO must not drag
        # goodput down — the retried attempt is the client-visible one.
        t.observe(_record(ttft_s=9.0, tpot_s=9.0, reason="rerouted"))
        assert t.summary()["goodput_ratio"] is None
        t.observe(_record(ttft_s=0.01, tpot_s=0.001))
        s = t.summary()
        assert s["goodput_ratio"] == pytest.approx(1.0)
        assert s["finished_total"] == {"rerouted": 1, "stop": 1}


class TestRecordFinish:

    def test_replays_flight_recorder_trace(self):
        recorder = get_flight_recorder()
        recorder.reset_for_testing()
        t = SLOTracker(slo_ttft_ms=60000.0, slo_tpot_ms=60000.0)
        try:
            recorder.record("slo-req", "arrived")
            recorder.record("slo-req", "queued")
            recorder.record("slo-req", "scheduled")
            recorder.record("slo-req", "preempted", "swap")
            recorder.record("slo-req", "first_token")
            recorder.record("slo-req", "finished", "stop")
            t.record_finish("slo-req", 4)
            s = t.summary()
            assert s["window"] == 1
            assert s["finished_total"] == {"stop": 1}
            assert s["preemptions_total"] == {"swap": 1}
            assert s["goodput_ratio"] == pytest.approx(1.0)
        finally:
            recorder.reset_for_testing()

    def test_unknown_request_is_a_noop(self):
        recorder = get_flight_recorder()
        recorder.reset_for_testing()
        t = SLOTracker()
        t.record_finish("never-seen", 3)
        assert t.summary()["window"] == 0

    def test_disabled_tracker_records_nothing(self):
        t = SLOTracker(enabled=False)
        t.observe(_record(ttft_s=0.1, tpot_s=0.01))
        assert t.summary()["window"] == 0
