"""EngineWatchdog unit tests: stall detection (both conditions),
one-shot firing, recovery on a completed step, report contents, and the
disabled path — all driven via check_now(), no monitor thread."""
import threading
import time

from intellillm_tpu.obs.watchdog import EngineWatchdog, _thread_stacks


def make_watchdog(**kwargs):
    kwargs.setdefault("enabled", True)
    kwargs.setdefault("stall_s", 0.05)
    kwargs.setdefault("dispatch_s", 0.05)
    wd = EngineWatchdog(**kwargs)
    wd.attach(has_work=lambda: True,
              queue_depths=lambda: {"waiting": 2, "running": 1,
                                    "swapped": 0},
              kv_usage=lambda: {"device": 0.5, "cpu": 0.0},
              start_monitor=False)
    return wd


def test_no_stall_while_idle():
    wd = make_watchdog()
    wd._has_work = lambda: False
    wd.heartbeat_step()
    time.sleep(0.08)
    assert wd.check_now() is None
    assert wd.state == "ok"


def test_no_stall_before_threshold():
    wd = make_watchdog(stall_s=30.0, dispatch_s=30.0)
    wd.heartbeat_step()
    assert wd.check_now() is None


def test_step_stall_fires_once_then_recovers():
    wd = make_watchdog()
    wd.heartbeat_step()
    time.sleep(0.08)
    report = wd.check_now()
    assert report is not None
    assert report["reason"] == "no_step_progress"
    assert report["queue_depths"] == {"waiting": 2, "running": 1,
                                      "swapped": 0}
    assert report["kv_cache_usage"] == {"device": 0.5, "cpu": 0.0}
    assert report["thread_stacks"]  # at least this thread
    assert any("test_step_stall_fires_once" in stack
               for stack in report["thread_stacks"].values())
    assert wd.state == "stalled"
    # One-shot per episode: a second check does not re-fire.
    assert wd.check_now() is None
    assert len(wd.reports()) == 1

    # A completed step clears the episode; a fresh stall fires again.
    wd.heartbeat_step()
    assert wd.state == "ok"
    time.sleep(0.08)
    report2 = wd.check_now()
    assert report2 is not None
    assert len(wd.reports()) == 2
    assert wd.snapshot()["stalls_fired"] == 2


def test_dispatch_blocked_stall():
    wd = make_watchdog(stall_s=30.0, dispatch_s=0.05)
    wd.heartbeat_step()
    with wd.dispatch("decode_fused"):
        time.sleep(0.08)
        report = wd.check_now()
    assert report is not None
    assert report["reason"] == "dispatch_blocked"
    assert report["detail"]["program"] == "decode_fused"
    assert report["detail"]["blocked_for_s"] >= 0.05
    assert report["dispatch_in_flight"][0]["program"] == "decode_fused"


def test_inflight_dispatch_suppresses_step_stall():
    """A dispatch still within its own (long) threshold explains the
    missing step heartbeats — e.g. a cold XLA compile — so
    no_step_progress must not fire."""
    wd = make_watchdog(stall_s=0.05, dispatch_s=30.0)
    wd.heartbeat_step()
    with wd.dispatch("prefill"):
        time.sleep(0.08)
        assert wd.check_now() is None
    # Dispatch done but still no step: now it IS a stall.
    time.sleep(0.01)
    report = wd.check_now()
    assert report is not None and report["reason"] == "no_step_progress"


def test_disabled_watchdog_is_inert():
    wd = EngineWatchdog(enabled=False, stall_s=0.0, dispatch_s=0.0)
    wd.attach(has_work=lambda: True, start_monitor=False)
    wd.heartbeat_step()
    with wd.dispatch("prefill"):
        pass
    time.sleep(0.02)
    assert wd.check_now() is None
    assert wd.state == "ok"
    assert wd.snapshot()["enabled"] is False


def test_callback_failure_does_not_break_detection():
    def boom():
        raise RuntimeError("scheduler gone")
    wd = make_watchdog()
    wd._queue_depths = boom
    wd._kv_usage = boom
    time.sleep(0.08)
    report = wd.check_now()
    assert report is not None
    assert report["queue_depths"] is None
    assert report["kv_cache_usage"] is None


def test_monitor_thread_detects_stall():
    wd = make_watchdog(poll_s=0.02)
    wd.attach(has_work=lambda: True, start_monitor=True)
    try:
        deadline = time.monotonic() + 5.0
        while wd.state != "stalled" and time.monotonic() < deadline:
            time.sleep(0.01)
        assert wd.state == "stalled"
        assert len(wd.reports()) == 1
    finally:
        wd.reset_for_testing()


def test_thread_stacks_cover_other_threads():
    done = threading.Event()
    t = threading.Thread(target=done.wait, name="stuck-worker")
    t.start()
    try:
        stacks = _thread_stacks()
        assert any("stuck-worker" in label for label in stacks)
    finally:
        done.set()
        t.join()


def test_snapshot_shape():
    wd = make_watchdog(stall_s=1.0, dispatch_s=2.0)
    snap = wd.snapshot()
    assert snap["state"] == "ok"
    assert snap["stall_after_s"] == 1.0
    assert snap["dispatch_stall_after_s"] == 2.0
    assert snap["dispatch_in_flight"] == []
    assert snap["last_step_age_s"] >= 0.0
