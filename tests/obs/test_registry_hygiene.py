"""Registry-hygiene guard, now a thin wrapper over the `metric-hygiene`
lint rule (intellillm_tpu/analysis/rules/metric_hygiene.py): names must
carry the `intellillm_` prefix (one grafana namespace, no collisions
with other exporters), every module that registers collectors must
expose a `reset_for_testing` hook, and collectors live only in the
designated metrics modules. The rule also runs in the lint CI gate
(tests/analysis/test_tree_clean.py); this wrapper keeps the original
guard-the-guard assertions so the scrape itself can't rot."""
from intellillm_tpu.analysis.engine import load_project
from intellillm_tpu.analysis.rules.metric_hygiene import (
    MetricHygieneRule, prometheus_collector_calls)


def _metric_constructors():
    """(module, metric_name) for every collector constructed in-package."""
    found = []
    for mod in load_project().modules:
        for _, name in prometheus_collector_calls(mod):
            found.append((mod, name))
    return found


def _hygiene_violations():
    project = load_project()
    rule = MetricHygieneRule(project.settings)
    out = []
    for mod in project.modules:
        out.extend(rule.check(mod))
    return out


def test_constructors_are_found():
    # Guard the guard: the scrape must keep seeing the known collectors,
    # or the assertions below pass vacuously.
    names = {name for _, name in _metric_constructors()}
    assert len(names) >= 30, sorted(names)
    assert "intellillm_step_phase_seconds" in names
    assert "intellillm_device_hbm_bytes_in_use" in names
    assert "intellillm_swap_bytes_total" in names
    # Router families (PR 6) are in-package and covered by this guard.
    assert "intellillm_router_requests_total" in names
    assert "intellillm_router_routing_decisions_total" in names
    assert "intellillm_router_predicted_load_tokens" in names
    # Distributed-tracing families (PR 7).
    assert "intellillm_trace_exported_total" in names
    assert "intellillm_trace_hop_seconds" in names
    # Speculative-decoding families (PR 13).
    assert "intellillm_spec_draft_tokens_total" in names
    assert "intellillm_spec_accepted_tokens_total" in names
    assert "intellillm_spec_emitted_tokens_total" in names
    assert "intellillm_spec_current_k" in names
    assert "intellillm_spec_verify_waste_ratio" in names
    # Per-kernel cost-ledger families (PR 16).
    assert "intellillm_kernel_flops" in names
    assert "intellillm_kernel_bytes_accessed" in names
    assert "intellillm_kernel_hbm_peak_bytes" in names
    assert "intellillm_kernel_executables" in names
    assert "intellillm_kernel_mfu_costmodel" in names
    # Scheduler decision-tracing families (PR 17).
    assert "intellillm_sched_deferred_seconds_total" in names
    assert "intellillm_sched_decisions_total" in names
    # Workload-capture families (PR 18).
    assert "intellillm_workload_requests_total" in names
    assert "intellillm_workload_prompt_tokens_total" in names
    assert "intellillm_workload_output_tokens_total" in names
    # Numerics / output-integrity families (PR 19).
    assert "intellillm_numerics_rows_checked_total" in names
    assert "intellillm_numerics_anomalies_total" in names
    assert "intellillm_numerics_quarantined_total" in names
    assert "intellillm_kv_integrity_checksums_total" in names
    assert "intellillm_kv_integrity_mismatches_total" in names
    assert "intellillm_router_canary_runs_total" in names
    assert "intellillm_router_canary_divergence_total" in names
    assert "intellillm_router_canary_suspect" in names


def test_every_metric_name_is_prefixed():
    bad = [v.format() for v in _hygiene_violations()
           if "prefix" in v.message]
    assert not bad, (
        f"metrics without the intellillm_ prefix: {bad} — all exported "
        "series share one namespace")


def test_every_metrics_module_has_reset_hook():
    missing = [v.format() for v in _hygiene_violations()
               if "reset_for_testing" in v.message]
    assert not missing, (
        f"modules registering Prometheus collectors without a "
        f"reset_for_testing hook: {missing} — tests cannot unregister "
        "their collectors between engine rebuilds")


def test_collectors_only_in_designated_modules():
    # New with the lint suite: ad-hoc families outside obs/,
    # engine/metrics.py, router/metrics.py dodge the guards above.
    strays = [v.format() for v in _hygiene_violations()
              if "outside" in v.message]
    assert not strays, strays
