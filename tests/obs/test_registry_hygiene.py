"""Static registry-hygiene guard over every Prometheus metric
constructor in the package: names must carry the `intellillm_` prefix
(one grafana namespace, no collisions with other exporters), and any
module that registers collectors must expose a `reset_for_testing` hook
so tests can rebuild engines without duplicate-registration errors."""
import pathlib
import re

REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]
PACKAGE_DIR = REPO_ROOT / "intellillm_tpu"

# A prometheus_client collector construction: the metric name is the
# first (string literal) argument.
CONSTRUCTOR_RE = re.compile(
    r"\b(?:Counter|Gauge|Histogram|Summary)\(\s*[\"']([^\"']+)[\"']")


def _metric_constructors():
    """(path, metric_name) for every collector constructed in-package."""
    found = []
    for path in sorted(PACKAGE_DIR.rglob("*.py")):
        text = path.read_text(encoding="utf-8")
        for match in CONSTRUCTOR_RE.finditer(text):
            found.append((path, match.group(1)))
    return found


def test_constructors_are_found():
    # Guard the guard: the scrape must keep seeing the known collectors,
    # or the assertions below pass vacuously.
    names = {name for _, name in _metric_constructors()}
    assert len(names) >= 25, sorted(names)
    assert "intellillm_step_phase_seconds" in names
    assert "intellillm_device_hbm_bytes_in_use" in names
    assert "intellillm_swap_bytes_total" in names
    # Router families (PR 6) are in-package and covered by this guard.
    assert "intellillm_router_requests_total" in names
    assert "intellillm_router_routing_decisions_total" in names
    assert "intellillm_router_predicted_load_tokens" in names
    # Distributed-tracing families (PR 7).
    assert "intellillm_trace_exported_total" in names
    assert "intellillm_trace_hop_seconds" in names


def test_every_metric_name_is_prefixed():
    bad = [(str(p.relative_to(REPO_ROOT)), name)
           for p, name in _metric_constructors()
           if not name.startswith("intellillm_")]
    assert not bad, (
        f"metrics without the intellillm_ prefix: {bad} — all exported "
        "series share one namespace")


def test_every_metrics_module_has_reset_hook():
    modules = {p for p, _ in _metric_constructors()}
    missing = [str(p.relative_to(REPO_ROOT)) for p in sorted(modules)
               if "reset_for_testing" not in p.read_text(encoding="utf-8")]
    assert not missing, (
        f"modules registering Prometheus collectors without a "
        f"reset_for_testing hook: {missing} — tests cannot unregister "
        "their collectors between engine rebuilds")
