"""Workload capture (obs/workload.py): the bounded ring, the IWL1
round trip, attempt dedup across failover/disagg legs, the rotating
durable sink, and the raw-prompt privacy gate."""
import json
import os

import pytest

from intellillm_tpu.obs.workload import (WorkloadLog, base_trace_id,
                                         dump_iwl, get_workload_log,
                                         iwl_header, merge_workloads,
                                         parse_iwl, prompt_fingerprint,
                                         reset_workload_log_for_testing)


def _record(log, i, ts=None, trace_id=None, reason="finished",
            tokens=8, prompt=None):
    log.record(trace_id=trace_id or f"req-{i}", arrival_ts=ts or 100.0 + i,
               prompt_len=4 + i, prompt_hash=f"{i:016x}",
               sampling={"max_tokens": tokens, "temperature": 0.0},
               emitted_tokens=tokens, reason=reason, prompt=prompt)


def test_ring_is_bounded_and_ordered():
    log = WorkloadLog(enabled=True, export=False, max_entries=4)
    # Seal order is finish order, not arrival order: record backwards.
    for i in reversed(range(6)):
        _record(log, i)
    recs = log.records()
    assert len(recs) == 4  # two oldest arrivals evicted is NOT promised —
    # the ring drops the two earliest *seals* (arrivals 5 and 4 stay)
    assert [r["id"] for r in recs] == ["req-0", "req-1", "req-2", "req-3"]
    snap = log.snapshot(limit=2, offset=1)
    assert snap["count"] == 6
    assert snap["evicted"] == 2
    assert [r["id"] for r in snap["records"]] == ["req-2", "req-1"]


def test_disabled_log_records_nothing():
    log = WorkloadLog(enabled=False, export=False)
    _record(log, 0)
    assert log.records() == []
    assert log.snapshot()["count"] == 0


def test_raw_prompts_gated_off_by_default():
    hashed = WorkloadLog(enabled=True, raw=False, export=False)
    _record(hashed, 0, prompt="the secret prompt")
    assert "prompt" not in hashed.records()[0]
    raw = WorkloadLog(enabled=True, raw=True, export=False)
    _record(raw, 0, prompt="the secret prompt")
    assert raw.records()[0]["prompt"] == "the secret prompt"


def test_prompt_fingerprint_stable_and_short():
    a = prompt_fingerprint("hello world", [1, 2, 3])
    assert a == prompt_fingerprint("hello world", [9, 9])  # text wins
    assert len(a) == 16 and int(a, 16) >= 0
    # Token-id fallback when the engine path has no prompt text.
    b = prompt_fingerprint(None, [1, 2, 3])
    assert b == prompt_fingerprint(None, [1, 2, 3])
    assert b != prompt_fingerprint(None, [1, 2, 4])


def test_iwl_round_trip_rebases_offsets():
    log = WorkloadLog(enabled=True, export=False)
    _record(log, 1, ts=50.5)
    _record(log, 0, ts=50.0)
    text = log.iwl_text(source="test")
    header, recs = parse_iwl(text)
    assert header["iwl"] == 1
    assert header["source"] == "test"
    assert header["requests"] == 2
    assert [r["id"] for r in recs] == ["req-0", "req-1"]
    assert [r["t"] for r in recs] == [0.0, 0.5]
    # Round trip: dump(parse(text)) carries the same records.
    _, again = parse_iwl(dump_iwl(recs, source="test"))
    assert [(r["id"], r["t"]) for r in again] == \
        [(r["id"], r["t"]) for r in recs]


def test_parse_iwl_rejects_bad_headers():
    with pytest.raises(ValueError):
        parse_iwl("")
    with pytest.raises(ValueError):
        parse_iwl(json.dumps({"not": "a header"}) + "\n")
    with pytest.raises(ValueError):
        parse_iwl(json.dumps({"iwl": 99}) + "\n")
    header, recs = parse_iwl(json.dumps(iwl_header(source="x")) + "\n")
    assert recs == []


def test_merge_dedups_attempts_prefers_finished():
    assert base_trace_id("abc#f1") == "abc"
    assert base_trace_id("abc#p0") == "abc"
    assert base_trace_id("abc") == "abc"
    a = WorkloadLog(enabled=True, export=False)
    b = WorkloadLog(enabled=True, export=False)
    # Same request seen on two replicas: the rerouted attempt on A, the
    # finished retry (#f1 suffix) on B. Merge keeps one record and
    # prefers the finished outcome.
    _record(a, 0, ts=10.0, trace_id="req-x", reason="rerouted", tokens=0)
    _record(b, 0, ts=10.2, trace_id="req-x#f1", reason="finished",
            tokens=8)
    _record(b, 1, ts=11.0, trace_id="req-y", reason="finished")
    merged, deduped = merge_workloads([a.records(), b.records()])
    assert deduped == 1
    assert [r["id"] for r in merged] == ["req-x#f1", "req-y"]
    assert merged[0]["outcome"]["reason"] == "finished"


def test_export_sink_writes_headers_and_rotates(tmp_path):
    log = WorkloadLog(enabled=True, export=True, raw=False,
                      workload_dir=str(tmp_path), max_bytes=400,
                      max_files=3, hop="unit")
    for i in range(12):
        _record(log, i)
    files = log.files()
    assert log.path in files and len(files) > 1  # rotation happened
    for name in files:
        lines = open(name).read().splitlines()
        hdr = json.loads(lines[0])
        # every sink file is self-describing IWL1
        assert hdr["iwl"] == 1 and hdr["source"] == "unit"
    # no file beyond max_files - 1 rotations
    assert not os.path.exists(f"{log.path}.3")


def test_record_seq_group_duck_typed_and_never_raises():
    class Params:
        max_tokens, temperature, top_p = 16, 0.0, 1.0

    class Group:
        request_id = "sg-1"
        prompt = "hi there"
        prompt_token_ids = [1, 2, 3]
        sampling_params = Params()
        lora_int_id = 0

        def __init__(self):
            import time
            self.arrival_time = time.monotonic() - 0.25

    log = WorkloadLog(enabled=True, export=False)
    log.record_seq_group(Group(), emitted_tokens=16, reason="finished")
    (rec,) = log.records()
    assert rec["id"] == "sg-1"
    assert rec["prompt_len"] == 3
    assert rec["sampling"]["max_tokens"] == 16
    assert rec["outcome"] == {"tokens": 16, "reason": "finished"}
    # A hostile seq_group must not raise into the engine finish path.
    log.record_seq_group(object(), emitted_tokens=1, reason="finished")
    assert len(log.records()) == 1


def test_singleton_reset(monkeypatch, tmp_path):
    monkeypatch.setenv("INTELLILLM_WORKLOAD_DIR", str(tmp_path))
    reset_workload_log_for_testing()
    try:
        log = get_workload_log()
        assert log is get_workload_log()
        _record(log, 0)
        assert log.snapshot()["count"] == 1
        reset_workload_log_for_testing()
        assert get_workload_log().snapshot()["count"] == 0
    finally:
        reset_workload_log_for_testing()
