"""Unit tests for obs/numerics.py and its read surfaces: the sentinel
tracker (panel observation, masking semantics, quarantine hand-off),
the sampled KV-integrity auditor (a flipped byte between swap-out and
swap-in must be caught), the canary ledger, the three new alert rules,
the black-box flush, and wdiff's numerics section directions."""
import json

import numpy as np
import pytest

from intellillm_tpu.obs import numerics as numerics_mod
from intellillm_tpu.obs.alerts import (KVIntegrityMismatchRule,
                                       NumericsAnomalyRule,
                                       SpecAcceptCollapseRule,
                                       built_in_rules)
from intellillm_tpu.obs.diff import diff_summaries, metric_direction
from intellillm_tpu.obs.numerics import (CanaryLedger, KVIntegrityAuditor,
                                         get_canary_ledger, get_kv_audit,
                                         get_numerics_tracker)


@pytest.fixture(autouse=True)
def _fresh_singletons():
    numerics_mod.reset_for_testing()
    yield
    numerics_mod.reset_for_testing()


def _panel(rows):
    """[B, 5] float32 sentinel panel from (nan, inf, max_abs, top1,
    entropy) tuples — what the mixed dispatch fetches."""
    return np.asarray(rows, np.float32)


class TestNumericsTracker:

    def test_clean_step_counts_rows_only(self):
        tracker = get_numerics_tracker()
        tracker.configure(enabled=True)
        stats = _panel([(0, 0, 12.5, 0.9, 0.4), (0, 0, 8.0, 0.5, 1.2)])
        tracker.observe_step(stats, [(0, ("req-a", 0)), (1, ("req-b", 0))])
        snap = tracker.snapshot()
        assert snap["rows_checked"] == 2
        assert snap["anomalies"] == {"nan": 0, "inf": 0, "max_abs": 0}
        assert tracker.take_quarantine("req-a") is None
        assert tracker.last_anomaly_age_s() is None
        assert snap["last_step"]["rows"] == 2

    def test_nan_row_quarantines_that_request_only(self):
        tracker = get_numerics_tracker()
        tracker.configure(enabled=True)
        stats = _panel([(3, 0, 12.5, np.nan, np.nan),
                        (0, 0, 8.0, 0.5, 1.2)])
        tracker.observe_step(stats, [(0, ("bad", 7)), (1, ("good", 0))])
        assert tracker.snapshot()["anomalies"]["nan"] == 1
        assert tracker.take_quarantine("good") is None
        info = tracker.take_quarantine("bad")
        assert info is not None
        assert info["kinds"] == ["nan"]
        assert info["seq_id"] == 7
        # Popped exactly once; the engine won't double-abort.
        assert tracker.take_quarantine("bad") is None
        assert tracker.snapshot()["quarantined"] == 1
        assert tracker.last_anomaly_age_s() is not None

    def test_inf_and_max_abs_kinds(self):
        tracker = get_numerics_tracker()
        tracker.configure(enabled=True, max_abs_threshold=100.0)
        stats = _panel([(0, 2, 50.0, 0.9, 0.1),
                        (0, 0, 5000.0, 0.9, 0.1)])
        tracker.observe_step(stats, [(0, ("r-inf", 0)), (1, ("r-big", 0))])
        snap = tracker.snapshot()
        assert snap["anomalies"]["inf"] == 1
        assert snap["anomalies"]["max_abs"] == 1
        assert tracker.take_quarantine("r-inf")["kinds"] == ["inf"]
        assert tracker.take_quarantine("r-big")["kinds"] == ["max_abs"]

    def test_non_finite_max_abs_counts_as_nan(self):
        # A NaN that reached the max-abs reduction itself (the panel's
        # max_abs cell is NaN) still trips the nan sentinel.
        tracker = get_numerics_tracker()
        tracker.configure(enabled=True)
        stats = _panel([(0, 0, np.nan, 0.9, 0.1)])
        tracker.observe_step(stats, [(0, ("r", 0))])
        assert tracker.snapshot()["anomalies"]["nan"] == 1

    def test_inject_vector_consumed_once(self):
        tracker = get_numerics_tracker()
        tracker.configure(enabled=True)
        tracker.inject_nan("victim")
        rows = [("other", 0), ("victim", 0)]
        vec = tracker.inject_vector(rows, padded_n=4)
        assert vec.shape == (4,)
        assert np.isnan(vec[1]) and vec[0] == 0.0 and vec[2] == 0.0
        # Consumed: the next step's vector is clean again.
        assert not np.isnan(tracker.inject_vector(rows, padded_n=4)).any()

    def test_health_block_shape(self):
        block = get_numerics_tracker().health_block()
        assert set(block) == {"enabled", "rows_checked", "anomalies",
                              "quarantined"}


class TestKVIntegrityAuditor:

    def _arrs(self):
        rng = np.random.RandomState(7)
        return (rng.randn(2, 16, 4).astype(np.float32),
                rng.randn(2, 16, 4).astype(np.float32))

    def test_swap_roundtrip_verifies_clean(self):
        audit = get_kv_audit()
        audit.configure(enabled=True, sample=1.0)
        k, v = self._arrs()
        audit.record("swap_out", layer=0, block=3, k_arr=k, v_arr=v)
        assert audit.verify("swap_in", 0, 3, k, v) is True
        snap = audit.snapshot()
        assert snap["checksums"]["swap_out"] == 1
        assert snap["checksums"]["swap_in"] == 1
        assert sum(snap["mismatches"].values()) == 0
        assert audit.last_mismatch_age_s() is None

    def test_byte_flip_between_swap_out_and_swap_in_is_caught(self):
        audit = get_kv_audit()
        audit.configure(enabled=True, sample=1.0)
        k, v = self._arrs()
        audit.record("swap_out", layer=1, block=9, k_arr=k, v_arr=v)
        # One bit flips while the block sits in the host pool.
        corrupted = k.copy()
        corrupted.view(np.uint8).reshape(-1)[13] ^= 0x40
        assert audit.verify("swap_in", 1, 9, corrupted, v) is False
        snap = audit.snapshot()
        assert snap["mismatches"]["swap_in"] == 1
        assert snap["last_mismatch"]["layer"] == 1
        assert snap["last_mismatch"]["block"] == 9
        assert audit.last_mismatch_age_s() is not None

    def test_unsampled_block_verifies_none(self):
        audit = get_kv_audit()
        audit.configure(enabled=True, sample=1.0)
        k, v = self._arrs()
        # Nothing recorded for this (layer, block): no verdict.
        assert audit.verify("swap_in", 5, 5, k, v) is None

    def test_should_audit_deterministic_and_gated(self):
        audit = KVIntegrityAuditor()
        audit.configure(enabled=True, sample=0.25)
        picks = [audit.should_audit(layer, block)
                 for layer in range(4) for block in range(64)]
        # Deterministic: swap-out and swap-in always agree.
        assert picks == [audit.should_audit(layer, block)
                         for layer in range(4) for block in range(64)]
        assert any(picks) and not all(picks)
        audit.configure(enabled=False)
        assert audit.should_audit(0, 0) is False
        audit.configure(enabled=True, sample=0.0)
        assert audit.should_audit(0, 0) is False
        audit.configure(sample=1.0)
        assert audit.should_audit(0, 0) is True

    def test_export_import_paths_count_only(self):
        audit = get_kv_audit()
        audit.configure(enabled=True, sample=1.0)
        k, v = self._arrs()
        audit.record("export", 0, 1, k, v)
        audit.record("import", 0, 1, k, v)
        snap = audit.snapshot()
        assert snap["checksums"]["export"] == 1
        assert snap["checksums"]["import"] == 1
        # Export staging hashes are never kept for swap-in verification
        # (transit is the wire format's job).
        assert audit.verify("swap_in", 0, 1, k, v) is None


class TestCanaryLedger:

    def test_record_run_and_snapshot(self):
        ledger = CanaryLedger(now_fn=lambda: 100.0)
        ledger.record_run({"r0": "aaaa", "r1": "aaaa", "r2": "bbbb"},
                          reference="aaaa", suspects=["r2"])
        ledger.record_run({"r0": "aaaa", "r1": "aaaa", "r2": "bbbb"},
                          reference="aaaa", suspects=["r2"])
        snap = ledger.snapshot()
        assert snap["runs_total"] == 2
        assert snap["reference_digest"] == "aaaa"
        assert snap["suspects"] == ["r2"]
        assert snap["divergence_total"] == {"r2": 2}
        assert snap["verdicts"]["r0"]["suspect"] is False
        assert ledger.suspects() == ["r2"]

    def test_reconvergence_clears_suspects(self):
        ledger = CanaryLedger(now_fn=lambda: 100.0)
        ledger.record_run({"r0": "a", "r1": "b"}, "a", ["r1"])
        ledger.record_run({"r0": "a", "r1": "a"}, "a", [])
        assert ledger.suspects() == []
        # ...but the per-replica divergence history is kept.
        assert ledger.snapshot()["divergence_total"] == {"r1": 1}


class _FakeHistory:
    def __init__(self, deltas):
        self._deltas = deltas

    def delta(self, name, window_s, now=None):
        return self._deltas.get(name)


class TestAlertRules:

    def test_numerics_rule_no_data_while_disabled(self):
        get_numerics_tracker().configure(enabled=False)
        rule = NumericsAnomalyRule(window_s=60.0)
        active, value, detail = rule.evaluate(None, now=0.0)
        assert active is None
        assert "disabled" in detail

    def test_numerics_rule_fires_on_fresh_anomaly(self):
        tracker = get_numerics_tracker()
        tracker.configure(enabled=True)
        rule = NumericsAnomalyRule(window_s=60.0)
        active, value, _ = rule.evaluate(None, now=0.0)
        assert active is False and value == 0.0
        tracker.observe_step(_panel([(1, 0, 1.0, np.nan, np.nan)]),
                             [(0, ("r", 0))])
        active, value, detail = rule.evaluate(None, now=0.0)
        assert active is True
        assert "quarantined" in detail

    def test_kv_rule_fires_on_mismatch(self):
        audit = get_kv_audit()
        audit.configure(enabled=True, sample=1.0)
        rule = KVIntegrityMismatchRule(window_s=60.0)
        active, _, _ = rule.evaluate(None, now=0.0)
        assert active is False
        k = np.ones((2, 4), np.float32)
        audit.record("swap_out", 0, 0, k, k)
        audit.verify("swap_in", 0, 0, k + 1, k)
        active, _, detail = rule.evaluate(None, now=0.0)
        assert active is True
        assert "mismatch" in detail

    def test_spec_collapse_rule(self):
        rule = SpecAcceptCollapseRule(window_s=60.0, min_accept=0.1,
                                      min_drafts=64.0)
        # No speculative decoding running: series absent, no verdict.
        active, _, _ = rule.evaluate(_FakeHistory({}), now=0.0)
        assert active is None
        # Too few drafts for a meaningful rate.
        active, _, _ = rule.evaluate(_FakeHistory({
            "intellillm_spec_draft_tokens_total": 8.0,
            "intellillm_spec_accepted_tokens_total": 0.0}), now=0.0)
        assert active is False
        # Collapse: 2% acceptance over a real draft volume.
        active, value, _ = rule.evaluate(_FakeHistory({
            "intellillm_spec_draft_tokens_total": 1000.0,
            "intellillm_spec_accepted_tokens_total": 20.0}), now=0.0)
        assert active is True and value == 0.02
        # Healthy acceptance stays quiet.
        active, _, _ = rule.evaluate(_FakeHistory({
            "intellillm_spec_draft_tokens_total": 1000.0,
            "intellillm_spec_accepted_tokens_total": 700.0}), now=0.0)
        assert active is False

    def test_rules_are_registered(self):
        names = {r.name for r in built_in_rules()}
        assert {"numerics_anomaly", "kv_integrity_mismatch",
                "spec_accept_collapse"} <= names


class TestBlackBoxAndDiff:

    def test_black_box_dump_includes_numerics_and_canary(self, tmp_path):
        from intellillm_tpu.obs.trace_export import flush_black_box
        get_numerics_tracker().configure(enabled=True)
        get_canary_ledger().record_run({"r0": "a", "r1": "b"}, "a", ["r1"])
        path = flush_black_box("test", black_box_dir=str(tmp_path))
        with open(path, encoding="utf-8") as f:
            dump = json.load(f)
        assert dump["numerics"]["sentinels"]["enabled"] is True
        assert "kv_audit" in dump["numerics"]
        assert dump["canary"]["suspects"] == ["r1"]

    def test_wdiff_numerics_section_directions(self):
        base = {"numerics": {
            "sentinels": {"anomalies": {"nan": 0}, "quarantined": 0,
                          "rows_checked": 1000},
            "kv_audit": {"mismatches": {"swap_in": 0},
                         "tracked_digests": 10}}}
        cand = {"numerics": {
            "sentinels": {"anomalies": {"nan": 3}, "quarantined": 3,
                          "rows_checked": 1000},
            "kv_audit": {"mismatches": {"swap_in": 2},
                         "tracked_digests": 40}}}
        report = diff_summaries(base, cand)
        assert "numerics" in report["regressed_sections"]
        flagged = {r["metric"] for r in
                   report["sections"]["numerics"]["regressions"]}
        assert "sentinels.anomalies.nan" in flagged
        assert "sentinels.quarantined" in flagged
        assert "kv_audit.mismatches.swap_in" in flagged
        # Digest counts are identifiers, not magnitudes: never flagged.
        assert "kv_audit.tracked_digests" not in flagged

    def test_metric_directions(self):
        assert metric_direction("sentinels.anomalies.nan") == "lower"
        assert metric_direction("kv_audit.mismatches.swap_in") == "lower"
        assert metric_direction("canary.divergence_total.r2") == "lower"
        assert metric_direction("reference_digest") is None
        # The guard the _LOWER_BETTER comment documents: a bare "nan"
        # fragment would swallow every per-tenant metric.
        assert metric_direction("per_tenant_requests") is None
