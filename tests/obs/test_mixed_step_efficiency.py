"""Mixed-step (chunked prefill) telemetry attribution.

Acceptance: a chunked-prefill run must report real prefill vs decode
token counts with NO double counting — every prompt token appears under
`intellillm_tokens_total{phase=prefill,kind=real}` exactly once (across
however many chunks it was split into), every decode row exactly once
under phase=decode — plus sane fill ratios and MFU inputs, and the
mixed flat-batch program tracked under its own "mixed" label in the
XLA compile tracker.
"""
import pytest

from intellillm_tpu import LLM, SamplingParams
from intellillm_tpu.obs import get_compile_tracker, get_efficiency_tracker

PROMPTS = [
    "hello my name is",
    "the president of the united states is",
    "the capital of france is",
    " ".join(["the cat runs fast and the dog"] * 4),  # 28 tokens
]
MAX_TOKENS = 8


@pytest.fixture
def trackers():
    eff = get_efficiency_tracker()
    comp = get_compile_tracker()
    eff.reset_for_testing()
    comp.reset_for_testing()
    yield eff, comp
    eff.reset_for_testing()
    comp.reset_for_testing()


def test_mixed_steps_attribute_tokens_exactly_once(tiny_opt_dir, trackers):
    eff, comp = trackers
    llm = LLM(model=tiny_opt_dir, dtype="float32",
              num_device_blocks_override=128, max_model_len=128,
              max_num_seqs=8, max_paddings=512, num_decode_steps=1,
              enable_chunked_prefill=True, max_num_batched_tokens=8)
    # Drop warm-up dispatches: only the serving steps should be counted.
    # The reset also wipes the FLOPs model derived at engine init, so
    # re-derive it — the MFU assertions below need a denominator input.
    eff.reset_for_testing()
    comp.reset_for_testing()
    eff.configure_model(llm.llm_engine.model_config)

    engine = llm.llm_engine
    tok = engine.tokenizer
    prompt_lens = [len(tok.encode(p)) for p in PROMPTS]
    for i, p in enumerate(PROMPTS):
        engine.add_request(str(i), p, SamplingParams(
            temperature=0.0, max_tokens=MAX_TOKENS, ignore_eos=True))
    outs = list(llm._run_engine(use_tqdm=False))
    assert all(len(o.outputs[0].token_ids) == MAX_TOKENS for o in outs)

    snap = eff.snapshot()
    tokens = snap["tokens_total"]

    # Every prompt token prefilled exactly once across all its chunks
    # (roomy pool → no preemption → no re-prefill), despite prompts
    # being split by the 8-token budget and sharing flat batches with
    # decode rows.
    assert tokens["prefill"]["real"] == sum(prompt_lens), (
        f"prefill real tokens {tokens['prefill']['real']} != "
        f"prompt tokens {sum(prompt_lens)} — chunk tokens double- or "
        "under-counted")

    # Each generated token except the final-chunk sample comes from one
    # real decode row in exactly one step.
    expected_decode = sum(MAX_TOKENS - 1 for _ in PROMPTS)
    assert tokens["decode"]["real"] == expected_decode, (
        f"decode real tokens {tokens['decode']['real']} != "
        f"{expected_decode} — decode rows double-counted or chunk rows "
        "leaked into the decode phase")

    # Flat-batch padding is accounted (pad > 0: budget 8 pads to the
    # 16-row token bucket) and ratios stay in range.
    assert tokens["decode"]["pad"] > 0 or tokens["prefill"]["pad"] > 0
    assert snap["pad_fraction"] is not None and 0 < snap["pad_fraction"] < 1
    fills = snap["fill_ratio_avg"]
    assert 0 < fills["prefill"]["batch"] <= 1
    assert 0 < fills["decode"]["batch"] <= 1
    # MFU inputs: steps counted, FLOPs model derived.
    assert snap["steps"] > 0
    assert snap["flops_per_token"] and snap["flops_per_token"] > 0

    # The mixed flat-batch program is tracked under its own label.
    csnap = comp.snapshot()
    mixed_programs = [p for p in csnap["compiles"] if p == "mixed"]
    assert mixed_programs, (
        f"no 'mixed' program in compile tracker: {csnap['compiles']}")


def test_chunked_off_still_runs_only_mixed_family(tiny_opt_dir, trackers):
    """--disable-chunked-prefill changes ADMISSION (whole-prompt chunks),
    not execution: the compile tracker must show only the mixed program
    family — the legacy homogeneous prefill program is gone — and
    prefill tokens still attribute exactly once."""
    eff, comp = trackers
    llm = LLM(model=tiny_opt_dir, dtype="float32",
              num_device_blocks_override=128, max_model_len=128,
              max_num_seqs=8, max_paddings=512, num_decode_steps=1,
              enable_chunked_prefill=False)
    eff.reset_for_testing()
    comp.reset_for_testing()
    engine = llm.llm_engine
    tok = engine.tokenizer
    prompt_lens = [len(tok.encode(p)) for p in PROMPTS]
    for i, p in enumerate(PROMPTS):
        engine.add_request(str(i), p, SamplingParams(
            temperature=0.0, max_tokens=MAX_TOKENS, ignore_eos=True))
    list(llm._run_engine(use_tqdm=False))

    compiles = comp.snapshot()["compiles"]
    assert "mixed" in compiles, compiles
    allowed = {"mixed", "decode_fused", "decode_cont", "decode_teacher"}
    assert set(compiles) <= allowed, (
        f"non-mixed-family program dispatched: {compiles}")
    tokens = get_efficiency_tracker().snapshot()["tokens_total"]
    assert tokens["prefill"]["real"] == sum(prompt_lens)
