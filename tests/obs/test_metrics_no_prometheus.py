"""StatLogger must degrade gracefully when prometheus_client is absent
(the engine never requires it — `serve` extra only)."""
import importlib
import sys

import intellillm_tpu.engine.metrics as metrics_mod


def test_statlogger_without_prometheus(monkeypatch):
    # Unregister the real singleton's collectors BEFORE hiding the
    # package (afterwards the module can't reach the registry), then
    # make `import prometheus_client` raise ImportError and rebuild the
    # module so its _PROMETHEUS flag flips off.
    metrics_mod._Metrics.reset_for_testing()
    monkeypatch.setitem(sys.modules, "prometheus_client", None)
    try:
        reloaded = importlib.reload(metrics_mod)
        assert reloaded._PROMETHEUS is False

        logger = reloaded.StatLogger(local_interval=0.0,
                                     labels={"model_name": "m"})
        assert logger.metrics is None
        stats = reloaded.Stats(
            now=1000.0, num_running=1, num_swapped=0, num_waiting=2,
            device_cache_usage=0.5, cpu_cache_usage=0.0,
            num_prompt_tokens=16, num_generation_tokens=4,
            time_to_first_tokens=[0.01],
            time_per_output_tokens=[0.002],
            time_e2e_requests=[0.1],
            spec_acceptance_rate=0.75,
            step_phase_times={"execute": 0.005, "schedule": 0.001},
            step_time=0.007)
        logger.log(stats)          # must not raise
        logger.log(stats)          # crosses local_interval: logs breakdown
    finally:
        monkeypatch.undo()
        restored = importlib.reload(metrics_mod)
        assert restored._PROMETHEUS is True
        restored._Metrics.reset_for_testing()


def test_spec_acceptance_rate_optional():
    from intellillm_tpu.engine.metrics import Stats
    stats = Stats(now=0.0, num_running=0, num_swapped=0, num_waiting=0,
                  device_cache_usage=0.0, cpu_cache_usage=0.0,
                  num_prompt_tokens=0, num_generation_tokens=0)
    assert stats.spec_acceptance_rate is None
    assert stats.step_phase_times == {}
    assert stats.step_time == 0.0
