"""StatLogger, SLOTracker, and EngineWatchdog must degrade gracefully
when prometheus_client is absent (the engine never requires it —
`serve` extra only) — plus coverage of the StatLogger interval log
lines (step breakdown + SLO percentiles/goodput)."""
import importlib
import sys

import pytest

import intellillm_tpu.engine.metrics as metrics_mod
import intellillm_tpu.obs.alerts as alerts_mod
import intellillm_tpu.obs.device_telemetry as devtel_mod
import intellillm_tpu.obs.efficiency as eff_mod
import intellillm_tpu.obs.history as history_mod
import intellillm_tpu.obs.slo as slo_mod
import intellillm_tpu.obs.watchdog as watchdog_mod


def _stats(reloaded, now):
    return reloaded.Stats(
        now=now, num_running=1, num_swapped=0, num_waiting=2,
        device_cache_usage=0.5, cpu_cache_usage=0.0,
        num_prompt_tokens=16, num_generation_tokens=4,
        time_to_first_tokens=[0.01],
        time_per_output_tokens=[0.002],
        time_e2e_requests=[0.1],
        spec_acceptance_rate=0.75,
        step_phase_times={"execute": 0.005, "schedule": 0.001},
        step_time=0.007)


def test_statlogger_interval_log_lines(monkeypatch):
    """Crossing local_interval must emit the throughput line, the step
    breakdown line, and (when the SLO window is non-empty) the rolling
    percentile/goodput line."""
    tracker = slo_mod.get_slo_tracker()
    tracker.reset_for_testing()
    tracker.configure(slo_ttft_ms=100.0, slo_tpot_ms=10.0)
    tracker.observe({"queue_wait_s": 0.02, "ttft_s": 0.05,
                     "tpot_s": 0.005, "e2e_s": 0.5,
                     "generation_tokens": 8, "preemptions": {},
                     "reason": "stop"})
    tracker.observe({"queue_wait_s": 0.04, "ttft_s": 0.5,
                     "tpot_s": 0.005, "e2e_s": 1.0,
                     "generation_tokens": 8, "preemptions": {},
                     "reason": "stop"})
    lines = []
    monkeypatch.setattr(metrics_mod.logger, "info",
                        lambda msg, *args: lines.append(msg % args))
    try:
        stat_logger = metrics_mod.StatLogger(local_interval=0.0,
                                             labels={"model_name": "m"})
        # last_local_log is initialized to time.monotonic(); pin it so the
        # synthetic stats.now deterministically crosses the interval.
        stat_logger.last_local_log = 999.0
        stat_logger.log(_stats(metrics_mod, now=1000.0))
        breakdown = [ln for ln in lines if "Step breakdown" in ln]
        assert breakdown and "execute" in breakdown[0]
        slo_lines = [ln for ln in lines if "Request SLO" in ln]
        assert slo_lines, lines
        line = slo_lines[0]
        assert "last 2 finishes" in line
        assert "queue-wait 20/40/40" in line
        assert "TTFT 50/500/500" in line
        # One of two finishes blew the 100ms TTFT SLO.
        assert "goodput 50.0%" in line
        assert "TTFT<=100ms, TPOT<=10ms" in line
    finally:
        tracker.reset_for_testing()
        if metrics_mod._PROMETHEUS:
            metrics_mod._Metrics.reset_for_testing()


def test_statlogger_slo_line_skipped_when_window_empty(monkeypatch):
    tracker = slo_mod.get_slo_tracker()
    tracker.reset_for_testing()
    lines = []
    monkeypatch.setattr(metrics_mod.logger, "info",
                        lambda msg, *args: lines.append(msg % args))
    try:
        stat_logger = metrics_mod.StatLogger(local_interval=0.0,
                                             labels={"model_name": "m"})
        stat_logger.last_local_log = 999.0
        stat_logger.log(_stats(metrics_mod, now=1000.0))
        assert [ln for ln in lines if "Avg prefill throughput" in ln]
        assert not [ln for ln in lines if "Request SLO" in ln]
    finally:
        if metrics_mod._PROMETHEUS:
            metrics_mod._Metrics.reset_for_testing()


def test_statlogger_without_prometheus(monkeypatch):
    # Unregister the real singleton's collectors BEFORE hiding the
    # package (afterwards the module can't reach the registry), then
    # make `import prometheus_client` raise ImportError and rebuild the
    # module so its _PROMETHEUS flag flips off.
    metrics_mod._Metrics.reset_for_testing()
    monkeypatch.setitem(sys.modules, "prometheus_client", None)
    try:
        reloaded = importlib.reload(metrics_mod)
        assert reloaded._PROMETHEUS is False

        logger = reloaded.StatLogger(local_interval=0.0,
                                     labels={"model_name": "m"})
        assert logger.metrics is None
        stats = reloaded.Stats(
            now=1000.0, num_running=1, num_swapped=0, num_waiting=2,
            device_cache_usage=0.5, cpu_cache_usage=0.0,
            num_prompt_tokens=16, num_generation_tokens=4,
            time_to_first_tokens=[0.01],
            time_per_output_tokens=[0.002],
            time_e2e_requests=[0.1],
            spec_acceptance_rate=0.75,
            step_phase_times={"execute": 0.005, "schedule": 0.001},
            step_time=0.007)
        logger.log(stats)          # must not raise
        logger.log(stats)          # crosses local_interval: logs breakdown
    finally:
        monkeypatch.undo()
        restored = importlib.reload(metrics_mod)
        assert restored._PROMETHEUS is True
        restored._Metrics.reset_for_testing()


def test_slo_tracker_without_prometheus(monkeypatch):
    """Every new SLO metric path (queue-time histogram, preemption and
    finished counters, generation-tokens histogram, goodput gauge) must
    work — including the goodput math — with prometheus_client absent."""
    slo_mod._SLOMetrics.reset_for_testing()
    monkeypatch.setitem(sys.modules, "prometheus_client", None)
    try:
        reloaded = importlib.reload(slo_mod)
        assert reloaded._PROMETHEUS is False

        tracker = reloaded.SLOTracker(slo_ttft_ms=100.0, slo_tpot_ms=10.0)
        assert tracker._metrics is None
        tracker.observe({"queue_wait_s": 0.02, "ttft_s": 0.100,
                         "tpot_s": 0.010, "e2e_s": 0.5,
                         "generation_tokens": 8,
                         "preemptions": {"swap": 1}, "reason": "stop"})
        tracker.observe({"queue_wait_s": 0.02, "ttft_s": 0.200,
                         "tpot_s": 0.010, "e2e_s": 0.5,
                         "generation_tokens": 8, "preemptions": {},
                         "reason": "length"})
        s = tracker.summary()
        # Boundary math intact: exactly-at-SLO is good, over is not.
        assert s["goodput_ratio"] == pytest.approx(0.5)
        assert s["window"] == 2
        assert s["finished_total"] == {"stop": 1, "length": 1}
        assert s["preemptions_total"] == {"swap": 1}
        assert s["queue_wait_ms"]["p50"] == pytest.approx(20.0)
    finally:
        monkeypatch.undo()
        restored = importlib.reload(slo_mod)
        assert restored._PROMETHEUS is True
        restored._SLOMetrics.reset_for_testing()


def test_watchdog_without_prometheus(monkeypatch):
    """A stall must still fire (report + state flip) without the
    intellillm_engine_stalls_total counter."""
    import time

    watchdog_mod._WatchdogMetrics.reset_for_testing()
    monkeypatch.setitem(sys.modules, "prometheus_client", None)
    try:
        reloaded = importlib.reload(watchdog_mod)
        assert reloaded._PROMETHEUS is False

        wd = reloaded.EngineWatchdog(enabled=True, stall_s=0.02,
                                     dispatch_s=30.0)
        wd.attach(has_work=lambda: True, start_monitor=False)
        assert wd._metrics is None
        time.sleep(0.04)
        report = wd.check_now()
        assert report is not None
        assert report["reason"] == "no_step_progress"
        assert wd.state == "stalled"
    finally:
        monkeypatch.undo()
        restored = importlib.reload(watchdog_mod)
        assert restored._PROMETHEUS is True
        restored._WatchdogMetrics.reset_for_testing()


def test_device_telemetry_without_prometheus(monkeypatch):
    """Every device-telemetry path — poll, headroom, ledger, swap
    accounting, snapshot — must work with prometheus_client absent (the
    plain-dict state backs /health/detail and serve_bench)."""
    devtel_mod._DeviceMetrics.reset_for_testing()
    monkeypatch.setitem(sys.modules, "prometheus_client", None)
    try:
        reloaded = importlib.reload(devtel_mod)
        assert reloaded._PROMETHEUS is False

        t = reloaded.DeviceTelemetry(enabled=True, poll_s=60.0,
                                     headroom_warn=0.05)
        assert t._metrics is None
        sample = t.poll_once()           # real CPU poll: null byte fields
        assert sample
        t.set_ledger({"params": 1000, "kv_pool": 2000}, log_table=False)
        t.record_swap("out", 2, 100)
        t.record_swap("in", 2, 100)
        t.record_swap("copy", 1, 300)
        snap = t.snapshot()
        assert snap["ledger_bytes"] == {"params": 1000, "kv_pool": 2000}
        assert snap["swap_bytes_total"] == {"in": 200, "out": 200,
                                            "copy": 300}
        assert snap["devices"]
    finally:
        monkeypatch.undo()
        restored = importlib.reload(devtel_mod)
        assert restored._PROMETHEUS is True
        restored._DeviceMetrics.reset_for_testing()


def test_statlogger_line_splits_throughput_and_adds_efficiency(
        monkeypatch):
    """The periodic line reports prefill/decode tok/s from the
    efficiency tracker's real-token counters, plus pad%% and MFU (n/a
    until a FLOPs model + peak are configured)."""
    from intellillm_tpu.obs.efficiency import get_efficiency_tracker
    slo_mod.get_slo_tracker().reset_for_testing()
    eff = get_efficiency_tracker()
    eff.reset_for_testing()
    eff.record_dispatch("prefill", 3, 4, real_tokens=30, padded_tokens=64,
                        len_real=10, len_padded=16)
    eff.record_dispatch("decode", 6, 8, real_tokens=6, padded_tokens=8,
                        width_real=3, width_padded=16)
    lines = []
    monkeypatch.setattr(metrics_mod.logger, "info",
                        lambda msg, *args: lines.append(msg % args))
    try:
        stat_logger = metrics_mod.StatLogger(local_interval=0.0,
                                             labels={"model_name": "m"})
        stat_logger.last_local_log = 999.0
        stat_logger.log(_stats(metrics_mod, now=1000.0))
        tline = [ln for ln in lines if "Avg prefill throughput" in ln]
        assert tline, lines
        line = tline[0]
        # Interval spans exactly 1 s, so the tracker's real-token deltas
        # are the rates verbatim.
        assert "Avg prefill throughput: 30.0 tok/s" in line
        assert "Avg decode throughput: 6.0 tok/s" in line
        # pad = (64-30) + (8-6) = 36 of 72 total tokens.
        assert "pad: 50.0%" in line
        assert "MFU: n/a" in line
    finally:
        eff.reset_for_testing()
        if metrics_mod._PROMETHEUS:
            metrics_mod._Metrics.reset_for_testing()


def test_statlogger_falls_back_without_tracker_data(monkeypatch):
    """Synthetic Stats with an empty efficiency tracker (disabled, or
    unit tests): the split falls back to the engine-side accumulators
    and pad%% reads n/a instead of a bogus 0."""
    from intellillm_tpu.obs.efficiency import get_efficiency_tracker
    slo_mod.get_slo_tracker().reset_for_testing()
    eff = get_efficiency_tracker()
    eff.reset_for_testing()
    lines = []
    monkeypatch.setattr(metrics_mod.logger, "info",
                        lambda msg, *args: lines.append(msg % args))
    try:
        stat_logger = metrics_mod.StatLogger(local_interval=0.0,
                                             labels={"model_name": "m"})
        stat_logger.last_local_log = 999.0
        stat_logger.log(_stats(metrics_mod, now=1000.0))
        line = [ln for ln in lines if "Avg prefill throughput" in ln][0]
        assert "Avg prefill throughput: 16.0 tok/s" in line
        assert "Avg decode throughput: 4.0 tok/s" in line
        assert "pad: n/a" in line
    finally:
        eff.reset_for_testing()
        if metrics_mod._PROMETHEUS:
            metrics_mod._Metrics.reset_for_testing()


def test_efficiency_without_prometheus(monkeypatch):
    """Every efficiency path — dispatch accounting, warm-up exclusion,
    MFU roll-up, snapshot — must work with prometheus_client absent
    (the plain-dict ledger backs /debug/efficiency and serve_bench)."""
    eff_mod._EfficiencyMetrics.reset_for_testing()
    monkeypatch.setitem(sys.modules, "prometheus_client", None)
    try:
        reloaded = importlib.reload(eff_mod)
        assert reloaded._PROMETHEUS is False

        t = reloaded.EfficiencyTracker(enabled=True)
        assert t._metrics is None
        t.record_dispatch("prefill", 3, 4, real_tokens=30,
                          padded_tokens=64, len_real=10, len_padded=16)
        t.record_dispatch("decode", 6, 8, real_tokens=6, padded_tokens=8,
                          width_real=3, width_padded=16)
        with t.warmup():
            t.record_dispatch("decode", 1, 8, real_tokens=1,
                              padded_tokens=8)
        t.record_step(0.01)              # must not raise
        snap = t.snapshot()
        assert snap["tokens_total"]["prefill"] == {"real": 30, "pad": 34}
        assert snap["tokens_total"]["decode"] == {"real": 6, "pad": 2}
        assert snap["warmup_excluded_dispatches"] == 1
        assert snap["fill_ratio_avg"]["prefill"]["batch"] == \
            pytest.approx(0.75)
        assert snap["fill_ratio_avg"]["decode"]["block_width"] == \
            pytest.approx(3 / 16)
        assert snap["top_waste"]
        assert snap["mfu"] is None       # no FLOPs model / peak known
    finally:
        monkeypatch.undo()
        restored = importlib.reload(eff_mod)
        assert restored._PROMETHEUS is True
        restored._EfficiencyMetrics.reset_for_testing()


def test_history_without_prometheus(monkeypatch):
    """The history store must sample, tier, and answer window queries
    with prometheus_client absent (the registry scrape just yields
    nothing; collectors still feed the rings that back /debug/history)."""
    history_mod._HistoryMetrics.reset_for_testing()
    monkeypatch.setitem(sys.modules, "prometheus_client", None)
    try:
        reloaded = importlib.reload(history_mod)
        assert reloaded._PROMETHEUS is False

        clock = {"t": 0.0}
        h = reloaded.MetricsHistory(enabled=True, interval_s=10.0,
                                    now_fn=lambda: clock["t"])
        assert h._metrics is None
        series = {}
        h.register_collector(lambda: dict(series))
        for i in range(12):
            clock["t"] = i * 10.0
            series["intellillm_test_gauge"] = float(i)
            h.sample_once()
        assert h.latest("intellillm_test_gauge") == 11.0
        assert len(h.query("intellillm_test_gauge", tier="raw")) == 12
        assert h.query("intellillm_test_gauge", tier="1m")
        assert h.avg("intellillm_test_gauge", 30.0) == pytest.approx(9.5)
        snap = h.snapshot()
        assert snap["series"] == 1
        assert snap["memory_bytes"] <= snap["memory_cap_bytes"]
    finally:
        monkeypatch.undo()
        restored = importlib.reload(history_mod)
        assert restored._PROMETHEUS is True
        restored._HistoryMetrics.reset_for_testing()


def test_alerts_without_prometheus(monkeypatch):
    """The full pending/firing/resolved cycle must run — snapshot,
    summary, page flag — without the intellillm_alerts gauge."""
    alerts_mod._AlertMetrics.reset_for_testing()
    monkeypatch.setitem(sys.modules, "prometheus_client", None)
    try:
        reloaded = importlib.reload(alerts_mod)
        assert reloaded._PROMETHEUS is False

        clock = {"t": 0.0}
        flag = {"active": True}
        rule = reloaded.AlertRule(
            "test_rule", severity="page",
            evaluate_fn=lambda h, now: (flag["active"], 1.0, "d"))
        manager = reloaded.AlertManager(enabled=True, rules=[rule],
                                        webhook_url="",
                                        now_fn=lambda: clock["t"])
        assert manager._metrics is None
        manager.evaluate_now()
        snap = manager.snapshot()
        assert snap["rules"]["test_rule"]["state"] == "firing"
        assert manager.page_firing() is True
        flag["active"] = False
        clock["t"] = 10.0
        manager.evaluate_now()
        assert manager.snapshot()["rules"]["test_rule"]["state"] \
            == "resolved"
        assert manager.summary()["page_firing"] is False
    finally:
        monkeypatch.undo()
        restored = importlib.reload(alerts_mod)
        assert restored._PROMETHEUS is True
        restored._AlertMetrics.reset_for_testing()


def test_spec_acceptance_rate_optional():
    from intellillm_tpu.engine.metrics import Stats
    stats = Stats(now=0.0, num_running=0, num_swapped=0, num_waiting=0,
                  device_cache_usage=0.0, cpu_cache_usage=0.0,
                  num_prompt_tokens=0, num_generation_tokens=0)
    assert stats.spec_acceptance_rate is None
    assert stats.step_phase_times == {}
    assert stats.step_time == 0.0
