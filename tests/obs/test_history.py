"""Unit tests for obs/history.py: fake-clock sampling into the
raw/1m/10m tiers, window queries and tier auto-selection, the
series-count cap, the memory cap under a 10k-sample soak, listener
dispatch, and the disabled store's no-op contract."""
import threading

import pytest

from intellillm_tpu.obs.history import (_MAX_POINTS_PER_SERIES,
                                        _POINT_BYTES, _RAW_KEEP,
                                        MetricsHistory)


class _Clock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _store(clock, **kw):
    kw.setdefault("enabled", True)
    kw.setdefault("interval_s", 10.0)
    return MetricsHistory(now_fn=clock, **kw)


def test_sample_collectors_feed_raw_and_tiers():
    clock = _Clock()
    h = _store(clock)
    vals = {"g": 1.0}
    h.register_collector(lambda: {"intellillm_test_gauge": vals["g"]})
    for i in range(12):  # two minutes at 10s
        clock.t = i * 10.0
        vals["g"] = float(i)
        h.sample_once()
    assert "intellillm_test_gauge" in h.series_names()
    raw = h.query("intellillm_test_gauge", tier="raw")
    assert len(raw) == 12
    assert raw[-1] == [110.0, 11.0]
    # 1m tier: bucket [0, 60) flushed once bucket [60, 120) opened
    # (mean avg(0..5) = 2.5), and the IN-PROGRESS bucket [60, 120) is
    # visible too with its running mean avg(6..11) = 8.5 — tier reads
    # must not lag a full bucket behind the data.
    one_m = h.query("intellillm_test_gauge", tier="1m")
    assert one_m == [[0.0, 2.5], [60.0, 8.5]]
    assert h.latest("intellillm_test_gauge") == 11.0


def test_window_query_avg_delta():
    clock = _Clock()
    h = _store(clock)
    series = {}
    h.register_collector(lambda: dict(series))
    for i in range(10):
        clock.t = i * 10.0
        series["intellillm_test_counter"] = float(i * 5)
        h.sample_once()
    # Window of 30s from t=90 keeps t in [60, 90].
    pts = h.query("intellillm_test_counter", window_s=30.0)
    assert [p[0] for p in pts] == [60.0, 70.0, 80.0, 90.0]
    assert h.avg("intellillm_test_counter", 30.0) == pytest.approx(37.5)
    assert h.delta("intellillm_test_counter", 30.0) == pytest.approx(15.0)
    # Unknown series: empty result, None aggregates.
    assert h.query("intellillm_nope", window_s=30.0) == []
    assert h.avg("intellillm_nope", 30.0) is None
    assert h.delta("intellillm_nope", 30.0) is None


def test_counter_reset_clamps_delta_at_zero():
    clock = _Clock()
    h = _store(clock)
    series = {"intellillm_test_counter": 100.0}
    h.register_collector(lambda: dict(series))
    h.sample_once()
    clock.t = 10.0
    series["intellillm_test_counter"] = 3.0  # process restart
    h.sample_once()
    assert h.delta("intellillm_test_counter", 60.0) == 0.0


def test_tier_autoselection_by_window():
    clock = _Clock()
    h = _store(clock)
    h.register_collector(lambda: {"intellillm_test_gauge": 1.0})
    for i in range(50):
        clock.t = i * 10.0
        h.sample_once()
    # Raw covers 360 * 10s = 1h: a 10-minute window stays raw.
    assert len(h.query("intellillm_test_gauge", window_s=600.0)) == 50
    # A 2h window exceeds raw coverage -> 1m tier (fewer, bucketed
    # points, each on a 60s boundary).
    coarse = h.query("intellillm_test_gauge", window_s=7200.0)
    assert coarse
    assert all(p[0] % 60.0 == 0.0 for p in coarse)
    assert len(coarse) < 50


def test_max_series_cap_drops_and_counts(monkeypatch):
    clock = _Clock()
    h = _store(clock, max_series=3)
    # Isolate from whatever intellillm_ collectors other tests left in
    # the live prometheus registry — counts must be deterministic.
    monkeypatch.setattr(h, "_scrape_registry", lambda: {})
    h.register_collector(lambda: {
        f"intellillm_test_{i}": float(i) for i in range(8)})
    h.sample_once()
    assert len(h.series_names()) == 3
    snap = h.snapshot()
    assert snap["series"] == 3
    assert snap["dropped_series"] == 5


def test_soak_10k_samples_stays_under_memory_cap(monkeypatch):
    clock = _Clock()
    h = _store(clock, max_series=8)
    monkeypatch.setattr(h, "_scrape_registry", lambda: {})
    h.register_collector(lambda: {
        f"intellillm_test_{i}": clock.t * (i + 1) for i in range(8)})
    for i in range(10_000):
        clock.t = i * 10.0
        h.sample_once()
    assert h.memory_bytes() <= h.memory_cap_bytes()
    assert h.memory_cap_bytes() == 8 * _MAX_POINTS_PER_SERIES * _POINT_BYTES
    for name in h.series_names():
        assert len(h.query(name, tier="raw")) == _RAW_KEEP
    snap = h.snapshot()
    assert snap["samples_taken"] == 10_000
    assert snap["memory_bytes"] <= snap["memory_cap_bytes"]


def test_registry_scrape_does_not_resurrect_collector_owned_series():
    """The router process registers the device-telemetry gauges (via
    get_device_telemetry) without ever polling them, leaving the
    unlabeled headroom gauge at prometheus's default 0.0 — the registry
    scrape must not record that as "0% headroom" (it would fire the
    page-severity hbm_headroom rule on every CPU router). Same contract
    as the traffic-gated goodput series: collector-owned keys come only
    from the built-in collector."""
    pytest.importorskip("prometheus_client")
    from intellillm_tpu.obs.device_telemetry import get_device_telemetry
    get_device_telemetry()  # registers intellillm_hbm_headroom_ratio
    clock = _Clock()
    h = _store(clock)
    h.sample_once()  # real registry scrape, no collectors attached
    assert "intellillm_hbm_headroom_ratio" not in h.series_names()
    assert "intellillm_slo_goodput_ratio" not in h.series_names()


def test_listeners_get_timestamp_and_errors_are_contained():
    clock = _Clock(5.0)
    h = _store(clock)
    seen = []

    def boom(t):
        raise RuntimeError("listener bug")

    h.register_listener(boom)
    h.register_listener(seen.append)
    h.register_collector(lambda: {"intellillm_test_gauge": 1.0})
    h.sample_once()
    assert seen == [5.0]


def test_collector_failure_does_not_kill_the_tick():
    clock = _Clock()
    h = _store(clock)

    def bad():
        raise RuntimeError("collector bug")

    h.register_collector(bad)
    h.register_collector(lambda: {"intellillm_test_gauge": 2.0,
                                  "intellillm_test_nan": float("nan")})
    h.sample_once()
    assert h.latest("intellillm_test_gauge") == 2.0
    # Non-finite values are skipped, not stored.
    assert "intellillm_test_nan" not in h.series_names()


def test_disabled_store_is_a_noop():
    clock = _Clock()
    h = _store(clock, enabled=False)
    h.register_collector(lambda: {"intellillm_test_gauge": 1.0})
    assert h.sample_once() == {}
    assert h.series_names() == []
    snap = h.snapshot()
    assert snap["enabled"] is False
    assert snap["samples_taken"] == 0
    h.attach()  # must not start a sampler thread
    assert h._sampler is None


def test_sampler_thread_lifecycle():
    h = MetricsHistory(enabled=True, interval_s=0.01)
    h.register_collector(lambda: {"intellillm_test_gauge": 1.0})
    h.attach(start_sampler=True)
    evt = threading.Event()
    h.register_listener(lambda t: evt.set())
    assert evt.wait(timeout=5.0)
    assert h._sampler is not None and h._sampler.is_alive()
    h.reset_for_testing()
    assert h._sampler is None
