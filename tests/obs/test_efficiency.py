"""Unit coverage for obs/efficiency.py: the analytic FLOPs model, the
peak-FLOPs resolution order (explicit > env > per-chip table > None),
warm-up exclusion, and the rolling-MFU NaN/finite transitions."""
import math

import pytest

from intellillm_tpu.obs import efficiency as eff_mod
from intellillm_tpu.obs.efficiency import (EfficiencyTracker,
                                           analytic_flops_per_token,
                                           resolve_peak_flops)


class _FakeHF:
    def __init__(self, **kwargs):
        self.__dict__.update(kwargs)


class _FakeModelConfig:
    """Just the ModelConfig surface analytic_flops_per_token touches."""

    def __init__(self, hidden=64, layers=2, heads=4, kv_heads=4,
                 head_size=16, vocab=100, **hf_kwargs):
        self._h, self._l, self._heads = hidden, layers, heads
        self._kv, self._hs, self._v = kv_heads, head_size, vocab
        self.hf_config = _FakeHF(**hf_kwargs)

    def get_hidden_size(self):
        return self._h

    def get_num_layers(self):
        return self._l

    def get_num_attention_heads(self):
        return self._heads

    def get_total_num_kv_heads(self):
        return self._kv

    def get_head_size(self):
        return self._hs

    def get_vocab_size(self):
        return self._v


@pytest.fixture
def tracker(monkeypatch):
    monkeypatch.delenv("INTELLILLM_PEAK_FLOPS", raising=False)
    monkeypatch.delenv("INTELLILLM_MFU_WINDOW", raising=False)
    monkeypatch.delenv("INTELLILLM_EFFICIENCY", raising=False)
    return EfficiencyTracker(enabled=True)


def test_analytic_flops_per_token_ungated():
    # h=64, layers=2, kv_dim=64, ffn_dim=128, vocab=100, relu MLP (2
    # mats): 2 * (2*(2*64*64 + 2*64*64 + 2*64*128) + 64*100) = 143872.
    cfg = _FakeModelConfig(ffn_dim=128, activation_function="relu")
    assert analytic_flops_per_token(cfg) == pytest.approx(143872.0)


def test_analytic_flops_per_token_gated_mlp_counts_third_matrix():
    base = _FakeModelConfig(intermediate_size=128, hidden_act="gelu")
    gated = _FakeModelConfig(intermediate_size=128, hidden_act="silu")
    # SwiGLU carries one extra h x inter matmul per layer:
    # delta = 2 * layers * h * inter = 2 * 2 * 64 * 128 = 32768.
    assert (analytic_flops_per_token(gated)
            - analytic_flops_per_token(base)) == pytest.approx(32768.0)


def test_analytic_flops_defaults_inter_to_4h():
    cfg = _FakeModelConfig()  # no intermediate_size/ffn_dim on hf_config
    # inter = 4 * 64 = 256, relu-style: 2*(2*(16384 + 2*64*256) + 6400)
    assert analytic_flops_per_token(cfg) == pytest.approx(209408.0)


def test_analytic_flops_none_on_broken_config():
    class Broken:
        hf_config = None

        def get_hidden_size(self):
            raise RuntimeError("no dims")

    assert analytic_flops_per_token(Broken()) is None


def test_resolve_peak_flops_table_and_env(monkeypatch):
    monkeypatch.delenv("INTELLILLM_PEAK_FLOPS", raising=False)
    # Substring match over lowercase device_kind.
    assert resolve_peak_flops("TPU v6e") == pytest.approx(918e12)
    assert resolve_peak_flops("TPU v5p") == pytest.approx(459e12)
    assert resolve_peak_flops("TPU v5 lite") == pytest.approx(197e12)
    assert resolve_peak_flops("cpu") is None
    assert resolve_peak_flops(None) is None
    # Env override beats the table (int8 serving / future chips).
    monkeypatch.setenv("INTELLILLM_PEAK_FLOPS", "2e15")
    assert resolve_peak_flops("TPU v6e") == pytest.approx(2e15)
    # Garbage env is ignored, not fatal.
    monkeypatch.setenv("INTELLILLM_PEAK_FLOPS", "fast")
    assert resolve_peak_flops("TPU v4") == pytest.approx(275e12)


def test_warmup_excludes_dispatches_from_ledger(tracker):
    """Acceptance: warm-up dispatches must not pollute the ledger —
    suppressed entirely, but counted as excluded."""
    with tracker.warmup():
        tracker.record_dispatch("decode", 1, 64, real_tokens=1,
                                padded_tokens=64, width_real=1,
                                width_padded=16)
        with tracker.warmup():  # nesting must not unsuppress early
            tracker.record_dispatch("decode", 1, 32, real_tokens=1,
                                    padded_tokens=32)
        tracker.record_dispatch("prefill", 1, 8, real_tokens=16,
                                padded_tokens=128, len_real=16,
                                len_padded=16)
    snap = tracker.snapshot()
    assert snap["tokens_total"]["decode"] == {"real": 0, "pad": 0}
    assert snap["tokens_total"]["prefill"] == {"real": 0, "pad": 0}
    assert snap["dispatches"] == {"prefill": 0, "decode": 0}
    assert snap["fill_ratio_avg"]["decode"]["block_width"] is None
    assert snap["top_waste"] == []
    assert snap["warmup_excluded_dispatches"] == 3
    assert tracker.warmup_excluded() == 3
    # After the context exits, recording resumes.
    tracker.record_dispatch("decode", 2, 4, real_tokens=2, padded_tokens=4)
    assert tracker.tokens_total()["decode"] == {"real": 2, "pad": 2}


def test_mfu_nan_without_peak_then_finite_with_override(tracker):
    cfg = _FakeModelConfig(ffn_dim=128)
    tracker.configure_model(cfg)  # CPU: no table entry -> peak None
    tracker.record_dispatch("decode", 4, 4, real_tokens=4, padded_tokens=4)
    assert tracker.record_step(0.01) is None
    assert tracker.rolling_mfu() is None
    snap = tracker.snapshot()
    assert snap["peak_flops"] is None
    assert snap["mfu"] is None          # JSON-safe: None, never NaN
    assert snap["flops_per_token"] == pytest.approx(143872.0)
    if tracker._metrics is not None:    # the gauge itself carries NaN
        assert math.isnan(tracker._metrics.gauge_mfu._value.get())

    tracker.configure(peak_flops=1e9)
    tracker.record_dispatch("decode", 4, 4, real_tokens=4, padded_tokens=4)
    mfu = tracker.record_step(0.01)
    # Window holds two steps: 8 real tokens over 0.02 s against 1e9
    # peak -> 8 * 143872 / (0.02 * 1e9).
    assert mfu == pytest.approx(8 * 143872.0 / (0.02 * 1e9))
    assert tracker.snapshot()["mfu"] == pytest.approx(mfu, abs=1e-6)


def test_explicit_peak_survives_attach_device(tracker):
    tracker.configure(peak_flops=5e12)
    tracker.attach_device()  # CPU would otherwise reset peak to None
    assert tracker.snapshot()["peak_flops"] == pytest.approx(5e12)
    # reset_for_testing drops the override (fresh resolution order).
    tracker.reset_for_testing()
    assert not hasattr(tracker, "_peak_override")


def test_mfu_window_is_rolling(monkeypatch):
    monkeypatch.delenv("INTELLILLM_PEAK_FLOPS", raising=False)
    monkeypatch.setenv("INTELLILLM_MFU_WINDOW", "2")
    t = EfficiencyTracker(enabled=True)
    t.configure(peak_flops=1e6)
    t._flops_per_token = 100.0
    t.record_dispatch("decode", 10, 10, real_tokens=10, padded_tokens=10)
    t.record_step(1.0)
    t.record_dispatch("decode", 10, 10, real_tokens=10, padded_tokens=10)
    t.record_step(1.0)
    # A third step evicts the first: only the last 2 steps count.
    t.record_dispatch("decode", 40, 40, real_tokens=40, padded_tokens=40)
    mfu = t.record_step(1.0)
    assert mfu == pytest.approx((10 + 40) * 100.0 / (2.0 * 1e6))


def test_disabled_tracker_is_a_noop(monkeypatch):
    monkeypatch.setenv("INTELLILLM_EFFICIENCY", "0")
    t = EfficiencyTracker()          # enabled resolved from env
    assert t.enabled is False
    t.record_dispatch("prefill", 4, 8, real_tokens=40, padded_tokens=128)
    assert t.record_step(0.01) is None
    snap = t.snapshot()
    assert snap["enabled"] is False
    assert snap["tokens_total"]["prefill"] == {"real": 0, "pad": 0}
    assert snap["steps"] == 0


def test_record_dispatch_clamps_and_attributes_buckets(tracker):
    # real > padded (defensive): pad clamps to 0, fill to 1.0.
    tracker.record_dispatch("prefill", 9, 8, real_tokens=130,
                            padded_tokens=128, len_real=20, len_padded=16)
    tracker.record_dispatch("prefill", 2, 8, real_tokens=20,
                            padded_tokens=128, len_real=10, len_padded=16)
    snap = tracker.snapshot()
    assert snap["tokens_total"]["prefill"] == {"real": 150, "pad": 108}
    assert snap["pad_fraction"] == pytest.approx(108 / 258, abs=1e-4)
    # Both dispatches share the (batch=8, len=16) bucket pair.
    assert len(snap["per_bucket"]) == 1
    worst = snap["top_waste"][0]
    assert (worst["phase"], worst["batch_bucket"],
            worst["inner_bucket"]) == ("prefill", 8, 16)
    assert worst["axis"] == "len"
    assert worst["dispatches"] == 2
    assert worst["pad_tokens"] == 108
