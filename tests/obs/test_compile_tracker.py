"""CompileTracker unit tests: exactly one compile per new jit bucket,
zero on cache hits, and snapshot contents."""
from intellillm_tpu.obs.compile_tracker import (CompileTracker,
                                                get_compile_tracker)


def test_first_call_is_compile_repeat_is_hit():
    t = CompileTracker(enabled=True)
    calls = []

    def fn(x, y=0):
        calls.append((x, y))
        return x + y

    assert t.call("prefill", (8, 16), fn, 1, y=2) == 3
    snap = t.snapshot()
    assert snap["compiles"] == {"prefill": 1}
    assert snap["cache_hits"] == {}
    assert snap["compile_time_seconds"]["prefill"] >= 0.0
    assert snap["live_executables"] == 1

    # Same bucket again: a cache hit, never a second compile.
    assert t.call("prefill", (8, 16), fn, 5, y=5) == 10
    snap = t.snapshot()
    assert snap["compiles"] == {"prefill": 1}
    assert snap["cache_hits"] == {"prefill": 1}
    assert calls == [(1, 2), (5, 5)]


def test_new_bucket_compiles_again():
    t = CompileTracker(enabled=True)
    fn = lambda: None  # noqa: E731
    t.call("decode_single", (8, 4), fn)
    t.call("decode_single", (16, 4), fn)  # different batch bucket
    t.call("decode_fused", (8, 4), fn)    # same key, different program
    snap = t.snapshot()
    assert snap["compiles"] == {"decode_single": 2, "decode_fused": 1}
    assert snap["cache_hits"] == {}
    assert snap["live_executables"] == 3


def test_many_hits_single_compile():
    t = CompileTracker(enabled=True)
    for _ in range(10):
        t.call("decode_cont", (4, 2, True), lambda: 1)
    snap = t.snapshot()
    assert snap["compiles"] == {"decode_cont": 1}
    assert snap["cache_hits"] == {"decode_cont": 9}


def test_kernel_dispatch_counts():
    t = CompileTracker(enabled=True)
    t.record_kernel_dispatch("pallas")
    t.record_kernel_dispatch("reference")
    t.record_kernel_dispatch("reference")
    assert t.snapshot()["kernel_dispatch"] == {"pallas": 1, "reference": 2}


def test_disabled_tracker_passes_through():
    t = CompileTracker(enabled=False)
    assert t.call("prefill", (1,), lambda v: v * 2, 21) == 42
    t.record_kernel_dispatch("pallas")
    snap = t.snapshot()
    assert snap["compiles"] == {}
    assert snap["kernel_dispatch"] == {}


def test_failed_first_dispatch_is_not_a_cache_hit():
    t = CompileTracker(enabled=True)

    def boom():
        raise RuntimeError("compile failed")

    try:
        t.call("prefill", (2,), boom)
    except RuntimeError:
        pass
    # A failed first dispatch (e.g. compile OOM) never produced an
    # executable: nothing is recorded and the retry counts as the
    # bucket's (one) real compile, not a hit.
    snap = t.snapshot()
    assert snap["compiles"] == {}
    assert snap["cache_hits"] == {}
    assert snap["live_executables"] == 0
    t.call("prefill", (2,), lambda: None)
    snap = t.snapshot()
    assert snap["compiles"] == {"prefill": 1}
    assert snap["cache_hits"] == {}
    # Only a successful dispatch claims the key: the next call is a hit.
    t.call("prefill", (2,), lambda: None)
    assert t.snapshot()["cache_hits"] == {"prefill": 1}


def test_global_tracker_reset():
    t = get_compile_tracker()
    assert get_compile_tracker() is t
    t.call("prefill", ("test-sentinel-key",), lambda: None)
    t.reset_for_testing()
    assert t.snapshot()["compiles"] == {}
