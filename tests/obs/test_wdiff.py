"""Summary diffing (obs/diff.py + tools/wdiff.py): section extraction,
direction inference, thresholds, the injected-regression acceptance
case (tenant caps off => tenancy section flagged), and the CLI's exit
codes."""
import json
import subprocess
import sys

import pytest

from intellillm_tpu.obs.diff import (DEFAULT_THRESHOLDS, diff_summaries,
                                     flatten, format_report, load_summary,
                                     metric_direction)


def _summary(**over):
    base = {
        "results": [{"request_throughput_rps": 10.0,
                     "output_tok_s": 1200.0,
                     "latency_percentiles_s": {"p50": 0.5, "p99": 1.0},
                     "ttft_percentiles_ms": {"p50": 40.0, "p99": 90.0}}],
        "slo": {"goodput_ratio": 0.98,
                "ttft_ms": {"p99": 80.0}, "tpot_ms": {"p99": 30.0}},
        "contention": {"deferred_seconds_by_cause": {"kv_pressure": 2.0}},
        "efficiency": {"mfu": 0.42, "pad_fraction": 0.2},
        "kernels": {"programs": {"mixed": {"compile_seconds_total": 3.0}}},
        "isolation": {"contention_vs_solo_tpot_p99_ratio": 1.3},
    }
    base.update(over)
    return base


def test_metric_direction_inference():
    assert metric_direction("request_throughput_rps") == "higher"
    assert metric_direction("goodput_ratio") == "higher"
    assert metric_direction("ttft_ms.p99") == "lower"
    assert metric_direction("deferred_seconds_by_cause.kv") == "lower"
    assert metric_direction("window") is None  # unknown => informational
    # Structural identifiers stay neutral even when a scored fragment
    # ("waste") appears higher up the dotted path.
    assert metric_direction("top_waste[2].batch_bucket") is None
    assert metric_direction("top_waste[2].pad_flops") == "lower"
    # fill_ratio is a utilization: higher is better despite "ratio" —
    # but "prefill" latencies must not catch the same fragment.
    assert metric_direction("fill_ratio_avg.decode.batch") == "higher"
    assert metric_direction("hops_ms.prefill.p50") == "lower"


def test_structural_fields_never_regress():
    """Bucket identities under `top_waste` shift between runs as the
    ranking reorders; they must not be scored as metrics. `slowest`
    carries per-request samples and is excluded from the slo view."""
    a = _summary(
        efficiency={"mfu": 0.42,
                    "top_waste": [{"batch_bucket": 1, "pad_flops": 5.0}]},
        slo={"goodput_ratio": 0.98, "ttft_ms": {"p99": 80.0},
             "slowest": [{"request_id": "r1", "e2e_ms": 100.0}]})
    b = _summary(
        efficiency={"mfu": 0.42,
                    "top_waste": [{"batch_bucket": 7, "pad_flops": 5.0}]},
        slo={"goodput_ratio": 0.98, "ttft_ms": {"p99": 80.0},
             "slowest": [{"request_id": "r9", "e2e_ms": 900.0}]})
    report = diff_summaries(a, b)
    assert report["regressed_sections"] == []
    assert report["verdict"].startswith("PASS")


def test_flatten_numeric_leaves_only():
    flat = flatten({"a": {"b": 1, "ok": True}, "c": [2.5, {"d": 3}],
                    "s": "text"})
    assert flat == {"a.b": 1.0, "c[0]": 2.5, "c[1].d": 3.0}


def test_identical_summaries_pass():
    report = diff_summaries(_summary(), _summary())
    assert report["regressed_sections"] == []
    assert report["verdict"].startswith("PASS")
    assert set(report["sections"]) <= set(DEFAULT_THRESHOLDS)


def test_injected_tenant_caps_off_regression_is_flagged():
    """The acceptance case: re-running with tenant caps disabled blows
    up the victim-isolation ratio (and leaks into SLO tail latency);
    wdiff must name the right sections and a REGRESSION verdict."""
    degraded = _summary(
        isolation={"contention_vs_solo_tpot_p99_ratio": 6.0},
        slo={"goodput_ratio": 0.6, "ttft_ms": {"p99": 80.0},
             "tpot_ms": {"p99": 240.0}})
    report = diff_summaries(_summary(), degraded)
    assert set(report["regressed_sections"]) == {"tenancy", "slo"}
    assert report["verdict"].startswith("REGRESSION")
    assert "tenancy" in report["verdict"]
    rows = report["sections"]["tenancy"]["regressions"]
    assert rows[0]["metric"].endswith("tpot_p99_ratio")
    text = format_report(report)
    assert "REGRESSED" in text and "tpot_p99_ratio" in text


def test_improvements_and_thresholds():
    better = _summary()
    better["results"][0]["output_tok_s"] = 2400.0  # +100%
    report = diff_summaries(_summary(), better)
    assert report["regressed_sections"] == []
    assert any(r["metric"].endswith("output_tok_s") for r in
               report["sections"]["throughput"]["improvements"])
    # A 5% throughput dip passes at the default 10% threshold but fails
    # when the caller tightens it.
    worse = _summary()
    worse["results"][0]["output_tok_s"] = 1140.0
    assert diff_summaries(_summary(), worse)["regressed_sections"] == []
    tight = diff_summaries(_summary(), worse,
                           thresholds={"throughput": 0.02})
    assert tight["regressed_sections"] == ["throughput"]


def test_near_zero_bases_are_not_noise():
    a = _summary(contention={"deferred_seconds_by_cause":
                             {"kv_pressure": 1e-9}})
    b = _summary(contention={"deferred_seconds_by_cause":
                             {"kv_pressure": 5e-9}})  # "5x" of nothing
    assert "contention" not in diff_summaries(a, b)["regressed_sections"]


def test_missing_sections_degrade_gracefully():
    report = diff_summaries({"results": _summary()["results"]},
                            {"slo": _summary()["slo"]})
    assert report["sections"] == {}
    assert report["verdict"].startswith("NO-DATA")


def test_load_summary_accepts_json_wrappers_and_stdout(tmp_path):
    plain = tmp_path / "plain.json"
    plain.write_text(json.dumps(_summary()))
    assert load_summary(str(plain))["slo"]["goodput_ratio"] == 0.98
    wrapped = tmp_path / "wrapped.json"
    wrapped.write_text(json.dumps({"serve_bench_summary": _summary()}))
    assert load_summary(str(wrapped))["efficiency"]["mfu"] == 0.42
    stdout = tmp_path / "run.log"
    stdout.write_text("booting...\n" + json.dumps({"x": 1}) + "\n"
                      + json.dumps({"serve_bench_summary": _summary()})
                      + "\n")
    assert load_summary(str(stdout))["efficiency"]["mfu"] == 0.42
    bad = tmp_path / "bad.log"
    bad.write_text("no json here\n")
    with pytest.raises(ValueError):
        load_summary(str(bad))


def _wdiff(args):
    return subprocess.run(
        [sys.executable, "-m", "intellillm_tpu.tools.wdiff"] + args,
        capture_output=True, text=True, timeout=120)


def test_wdiff_cli_exit_codes(tmp_path):
    good = tmp_path / "good.json"
    good.write_text(json.dumps({"serve_bench_summary": _summary()}))
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"serve_bench_summary": _summary(
        slo={"goodput_ratio": 0.4, "ttft_ms": {"p99": 500.0},
             "tpot_ms": {"p99": 30.0}})}))
    report_path = tmp_path / "report.txt"

    same = _wdiff([str(good), str(good)])
    assert same.returncode == 0, same.stdout + same.stderr
    assert "PASS" in same.stdout

    diff = _wdiff([str(good), str(bad), "--out", str(report_path)])
    assert diff.returncode == 1
    assert "REGRESSION" in diff.stdout and "slo" in diff.stdout
    assert "REGRESSION" in report_path.read_text()

    # --threshold loosens the gate back to passing
    loose = _wdiff([str(good), str(bad), "--threshold", "slo=9.9"])
    assert loose.returncode == 0, loose.stdout

    as_json = _wdiff([str(good), str(bad), "--json"])
    assert json.loads(as_json.stdout)["regressed_sections"] == ["slo"]

    missing = _wdiff([str(good), str(tmp_path / "nope.json")])
    assert missing.returncode == 2
