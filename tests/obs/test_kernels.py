"""Unit suite for the per-kernel cost ledger (obs/kernels.py): bucket
keying, introspection parsing (faked cost/memory analysis objects),
top-K ordering, the CPU/no-TPU degradation contract (nulls, never an
exception on the dispatch path), the cost-model MFU window, and the
profiler-trace parser against a faked trace-event file.

The module also prints its own wall-clock on teardown: the ledger tests
run inside the tier-1 870s budget, so the suite self-reports what it
costs (see docs/observability.md "Testing hooks")."""
import gzip
import json
import math
import sys
import time
from types import SimpleNamespace

import numpy as np
import pytest

from intellillm_tpu.obs.kernels import (KernelLedger, _parse_cost_analysis,
                                        get_kernel_ledger, parse_trace_dir)


@pytest.fixture(autouse=True, scope="module")
def _module_wallclock():
    t0 = time.perf_counter()
    yield
    # sys.__stderr__ bypasses pytest's capture: the tier-1 log always
    # shows what the ledger suite cost against the 870s budget.
    sys.__stderr__.write(
        f"\n[tier-1 budget] tests/obs/test_kernels.py wall-clock: "
        f"{time.perf_counter() - t0:.1f}s\n")


@pytest.fixture
def ledger():
    led = get_kernel_ledger()
    led.reset_for_testing()
    yield led
    led.reset_for_testing()


def _fake_fn(cost, mem, lower_raises=None):
    """A stand-in for a jitted function: .lower(...).compile() returns
    an object with cost_analysis()/memory_analysis()."""
    compiled = SimpleNamespace(cost_analysis=lambda: cost,
                               memory_analysis=lambda: mem)
    lowered = SimpleNamespace(compile=lambda: compiled)

    def lower(*args, **kwargs):
        if lower_raises is not None:
            raise lower_raises
        return lowered

    return SimpleNamespace(lower=lower)


_MEM = SimpleNamespace(argument_size_in_bytes=1000,
                       output_size_in_bytes=200,
                       temp_size_in_bytes=300,
                       generated_code_size_in_bytes=8)


def _dispatch_new(ledger, program, key, fn, elapsed=0.1):
    """Drive the prepare/commit pair the runner's _guarded_call uses."""
    pending = ledger.prepare(program, key, fn,
                             (np.ones((4,), np.float32),), {})
    assert pending is not None
    ledger.commit(pending, elapsed)


def test_keying_new_vs_seen_bucket(ledger):
    ledger.introspect_mode = "on"
    fn = _fake_fn([{"flops": 100.0, "bytes accessed": 50.0}], _MEM)
    _dispatch_new(ledger, "mixed", (8, 128), fn)
    # Same (program, key) again: counted, not re-introspected.
    assert ledger.prepare("mixed", (8, 128), fn, (), {}) is None
    # Same key under another program is a distinct executable.
    assert ledger.prepare("decode_fused", (8, 128), fn, (), {}) is not None

    snap = ledger.snapshot(top=8)
    entry = snap["executables"][0]
    assert entry["program"] == "mixed"
    assert entry["bucket"] == repr((8, 128))
    assert entry["flops"] == 100.0
    assert entry["bytes_accessed"] == 50.0
    assert entry["intensity_flops_per_byte"] == 2.0
    assert entry["hbm_peak_bytes"] == 1000 + 200 + 300 + 8
    assert entry["hbm_temp_bytes"] == 300
    assert entry["compile_seconds"] == pytest.approx(0.1)
    assert entry["dispatches"] == 2
    assert entry["analysis"] == "ok"


def test_cost_analysis_accepts_dict_and_list_forms():
    # jax returns a plain dict on some versions, [dict] on others.
    for raw in ({"flops": 7.0, "bytes accessed": 3.0},
                [{"flops": 7.0, "bytes accessed": 3.0}]):
        parsed = _parse_cost_analysis(raw)
        assert parsed["flops"] == 7.0
        assert parsed["bytes_accessed"] == 3.0
    # XLA's -1 means "unknown": normalized to null, never kept as a
    # negative that would poison sums.
    parsed = _parse_cost_analysis({"flops": -1, "bytes accessed": 4.0})
    assert parsed["flops"] is None
    # Empty / non-dict shapes: every value null, nothing raises.
    assert all(v is None for v in _parse_cost_analysis([]).values())
    assert _parse_cost_analysis(None) == {}
    assert _parse_cost_analysis("garbage") == {}


def test_top_k_ordering_analyzed_first_then_hottest(ledger):
    ledger.introspect_mode = "on"
    fn_small = _fake_fn([{"flops": 10.0, "bytes accessed": 5.0}], _MEM)
    fn_big = _fake_fn([{"flops": 900.0, "bytes accessed": 5.0}], _MEM)
    _dispatch_new(ledger, "mixed", ("small",), fn_small)
    _dispatch_new(ledger, "mixed", ("big",), fn_big)
    ledger.introspect_mode = "off"
    fn_null = _fake_fn(None, None)
    _dispatch_new(ledger, "decode_fused", ("null",), fn_null)
    for _ in range(3):
        assert ledger.prepare("decode_fused", ("null",), fn_null,
                              (), {}) is None

    snap = ledger.snapshot(top=2)
    assert snap["executables_total"] == 3
    assert [e["bucket"] for e in snap["executables"]] == [
        repr(("big",)), repr(("small",))]
    # Null entries sort after analyzed ones but are never dropped from
    # the aggregates.
    assert snap["programs"]["decode_fused"]["dispatches"] == 4
    assert snap["programs"]["decode_fused"]["flops_max"] is None


def test_failed_first_dispatch_forgets_the_key(ledger):
    ledger.introspect_mode = "on"
    fn = _fake_fn([{"flops": 1.0}], _MEM)
    pending = ledger.prepare("mixed", ("oom",), fn, (), {})
    assert pending is not None
    ledger.abandon(pending)  # dispatch raised
    # Retry is introspected fresh, not treated as a cache hit.
    assert ledger.prepare("mixed", ("oom",), fn, (), {}) is not None
    assert ledger.snapshot(top=1)["executables_total"] == 0


def test_introspection_failure_degrades_to_null_entry(ledger):
    """Satellite regression test: cost_analysis()/memory_analysis()
    raising or returning empty must produce a null entry — NaN-not-0 on
    gauges, None in JSON — and NEVER an exception on the dispatch
    path."""
    ledger.introspect_mode = "on"
    # lower() raises outright.
    fn_raise = _fake_fn(None, None, lower_raises=RuntimeError("no aot"))
    _dispatch_new(ledger, "mixed", ("raise",), fn_raise)  # must not throw
    # cost_analysis returns empty, memory_analysis raises.
    def _mem_raises():
        raise NotImplementedError("cpu")
    compiled = SimpleNamespace(cost_analysis=lambda: [],
                               memory_analysis=_mem_raises)
    fn_empty = SimpleNamespace(
        lower=lambda *a, **k: SimpleNamespace(compile=lambda: compiled))
    _dispatch_new(ledger, "mixed", ("empty",), fn_empty)

    snap = ledger.snapshot(top=8)
    by_bucket = {e["bucket"]: e for e in snap["executables"]}
    for bucket, status in ((repr(("raise",)), "error"),
                           (repr(("empty",)), "empty")):
        entry = by_bucket[bucket]
        assert entry["analysis"] == status
        for field in ("flops", "bytes_accessed", "hbm_peak_bytes",
                      "hbm_temp_bytes", "intensity_flops_per_byte"):
            assert entry[field] is None, (bucket, field)
    # The gauges read NaN (never 0) while no executable is analyzed.
    if ledger._metrics is not None:
        value = ledger._metrics.gauge_flops.labels("mixed")._value.get()
        assert math.isnan(value)
    # The JSON stays serializable with the nulls in place.
    json.dumps(snap)


def test_cpu_auto_mode_creates_null_entries(ledger, monkeypatch):
    """Default `auto` on the CPU backend: entries exist for every
    bucket, analysis fields are null — introspection's second compile
    is not paid on the tier-1 backend."""
    monkeypatch.delenv("INTELLILLM_KERNEL_INTROSPECT", raising=False)
    ledger.reset_for_testing()
    assert ledger.introspect_mode == "auto"
    import jax
    fn = jax.jit(lambda x: x + 1)
    x = np.ones((4,), np.float32)
    pending = ledger.prepare("mixed", ("cpu",), fn, (x,), {})
    fn(x)
    ledger.commit(pending, 0.05)
    entry = ledger.snapshot(top=1)["executables"][0]
    assert entry["analysis"] == "skipped"
    assert entry["flops"] is None and entry["bytes_accessed"] is None
    assert entry["compile_seconds"] == pytest.approx(0.05)


def test_mfu_costmodel_window_and_unknown_poisoning(ledger, monkeypatch):
    monkeypatch.setenv("INTELLILLM_PEAK_FLOPS", "1e6")
    ledger.reset_for_testing()
    ledger.introspect_mode = "on"
    fn = _fake_fn([{"flops": 5e3, "bytes accessed": 1.0}], _MEM)
    _dispatch_new(ledger, "mixed", ("a",), fn)
    # 5e3 FLOPs in 0.01s against a 1e6 FLOP/s peak: MFU = 0.5.
    assert ledger.record_step(0.01) == pytest.approx(0.5)
    assert ledger.snapshot(top=0)["mfu_costmodel"] == pytest.approx(0.5)
    if ledger._metrics is not None:
        assert ledger._metrics.gauge_mfu_costmodel._value.get() == \
            pytest.approx(0.5)

    # A dispatch with unknown FLOPs poisons the step: a partial sum
    # would silently understate MFU, so the window reads null instead.
    ledger.introspect_mode = "off"
    _dispatch_new(ledger, "mixed", ("null",), _fake_fn(None, None))
    assert ledger.record_step(0.01) is None
    assert ledger.snapshot(top=0)["mfu_costmodel"] is None
    if ledger._metrics is not None:
        assert math.isnan(
            ledger._metrics.gauge_mfu_costmodel._value.get())
    # Known steps rebuild the window afterwards.
    assert ledger.prepare("mixed", ("a",), fn, (), {}) is None
    assert ledger.record_step(0.01) == pytest.approx(0.5)


def test_merge_profile_top_k_and_shares(ledger):
    ops = [{"name": "fusion.1", "total_us": 600.0, "count": 3},
           {"name": "fusion.2", "total_us": 300.0, "count": 2},
           {"name": "copy.3", "total_us": 100.0, "count": 9}]
    block = ledger.merge_profile(ops, steps=4, top=2)
    assert block["steps"] == 4
    assert block["ops_total"] == 3
    assert block["total_us"] == pytest.approx(1000.0)
    assert [op["name"] for op in block["ops"]] == ["fusion.1", "fusion.2"]
    assert block["ops"][0]["share"] == pytest.approx(0.6)
    snap = ledger.snapshot(top=0)
    assert snap["profile"]["ops"][1]["share"] == pytest.approx(0.3)
    json.dumps(snap)


def _write_trace(path, events):
    doc = {"displayTimeUnit": "ns", "metadata": {}, "traceEvents": events}
    with gzip.open(path, "wt", encoding="utf-8") as f:
        json.dump(doc, f)


def test_parse_trace_dir_prefers_device_lanes(tmp_path):
    plugin_dir = tmp_path / "plugins" / "profile" / "2026_08_08"
    plugin_dir.mkdir(parents=True)
    _write_trace(plugin_dir / "host.trace.json.gz", [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "M", "pid": 9, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        # Host python frames: excluded once a device lane exists.
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": 9999.0,
         "name": "$pjit.py:330 cache_miss"},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": 5000.0,
         "name": "PjitFunction(step)"},
        # Device ops: summed by name across events.
        {"ph": "X", "pid": 9, "tid": 2, "ts": 0, "dur": 120.5,
         "name": "fusion.1"},
        {"ph": "X", "pid": 9, "tid": 2, "ts": 200, "dur": 79.5,
         "name": "fusion.1"},
        {"ph": "X", "pid": 9, "tid": 3, "ts": 0, "dur": 50.0,
         "name": "copy.2"},
        # Malformed events are skipped, not fatal.
        {"ph": "X", "pid": 9, "tid": 3, "ts": 0, "name": "no-dur"},
        {"ph": "C", "pid": 9, "name": "counter", "dur": 1.0},
    ])
    ops = parse_trace_dir(str(tmp_path))
    assert [op["name"] for op in ops] == ["fusion.1", "copy.2"]
    assert ops[0]["total_us"] == pytest.approx(200.0)
    assert ops[0]["count"] == 2


def test_parse_trace_dir_cpu_single_lane_filters_python_frames(tmp_path):
    _write_trace(tmp_path / "vm.trace.json.gz", [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": 9999.0,
         "name": "$profiler.py:91 start_trace"},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": 42.0,
         "name": "PjitFunction(decode)"},
    ])
    ops = parse_trace_dir(str(tmp_path))
    assert [op["name"] for op in ops] == ["PjitFunction(decode)"]


def test_parse_trace_dir_corrupt_or_missing_is_empty(tmp_path):
    assert parse_trace_dir(str(tmp_path / "nowhere")) == []
    bad = tmp_path / "x.trace.json.gz"
    bad.write_bytes(b"not gzip at all")
    assert parse_trace_dir(str(tmp_path)) == []


def test_reset_for_testing_clears_everything(ledger):
    ledger.introspect_mode = "on"
    _dispatch_new(ledger, "mixed", ("k",),
                  _fake_fn([{"flops": 1.0}], _MEM))
    ledger.merge_profile([{"name": "f", "total_us": 1.0, "count": 1}],
                         steps=1)
    ledger.record_step(0.01)
    ledger.reset_for_testing()
    snap = ledger.snapshot(top=4)
    assert snap["executables_total"] == 0
    assert snap["steps"] == 0
    assert snap["profile"] is None
    # The key space is forgotten too: the same bucket is "new" again.
    assert ledger.prepare("mixed", ("k",), _fake_fn(None, None),
                          (), {}) is not None


def test_disabled_ledger_is_a_noop(monkeypatch):
    monkeypatch.setenv("INTELLILLM_KERNEL_LEDGER", "0")
    led = get_kernel_ledger()
    led.reset_for_testing()
    try:
        assert led.prepare("mixed", ("k",), _fake_fn(None, None),
                           (), {}) is None
        assert led.record_step(0.01) is None
        assert led.snapshot(top=4)["enabled"] is False
    finally:
        monkeypatch.delenv("INTELLILLM_KERNEL_LEDGER")
        led.reset_for_testing()
