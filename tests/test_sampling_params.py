"""SamplingParams validation surface.

Role parity: reference `tests/test_sampling_params.py` (max_tokens=None)
plus the validation behaviors the reference checks implicitly via
`sampling_params.py:_verify_args`.
"""
import pytest

from intellillm_tpu import SamplingParams


def test_defaults():
    sp = SamplingParams()
    assert sp.n == 1 and sp.best_of == 1
    assert sp.max_tokens == 16
    assert sp.stop == [] and sp.stop_token_ids == []


def test_max_tokens_none_allowed():
    sp = SamplingParams(temperature=0.01, top_p=0.1, max_tokens=None)
    assert sp.max_tokens is None


@pytest.mark.parametrize("kwargs,match", [
    (dict(n=0), "n must be"),
    (dict(n=2, best_of=1), "best_of"),
    (dict(temperature=-0.1), "temperature"),
    (dict(top_p=0.0), "top_p"),
    (dict(top_p=1.5), "top_p"),
    (dict(top_k=0), "top_k"),
    (dict(top_k=-2), "top_k"),
    (dict(min_p=-0.5), "min_p"),
    (dict(max_tokens=0), "max_tokens"),
    (dict(logprobs=-1), "logprobs"),
    (dict(prompt_logprobs=-1), "prompt_logprobs"),
    (dict(presence_penalty=3.0), "presence_penalty"),
    (dict(frequency_penalty=-3.0), "frequency_penalty"),
    (dict(repetition_penalty=0.0), "repetition_penalty"),
])
def test_invalid_values_rejected(kwargs, match):
    with pytest.raises(ValueError, match=match):
        SamplingParams(**kwargs)


def test_beam_search_constraints():
    # Beam needs best_of > 1 and zero temperature knobs.
    SamplingParams(use_beam_search=True, best_of=2, temperature=0.0)
    with pytest.raises(ValueError):
        SamplingParams(use_beam_search=True, best_of=1)
    with pytest.raises(ValueError):
        SamplingParams(use_beam_search=True, best_of=2, temperature=0.7)
    # early_stopping only means something for beam search.
    with pytest.raises(ValueError):
        SamplingParams(early_stopping=True)


def test_greedy_best_of_must_be_one():
    with pytest.raises(ValueError, match="best_of"):
        SamplingParams(temperature=0.0, best_of=3)


def test_stop_normalization():
    sp = SamplingParams(stop="the")
    assert sp.stop == ["the"]
    sp = SamplingParams(stop=["a", "b"], stop_token_ids=[5])
    assert sp.stop == ["a", "b"] and sp.stop_token_ids == [5]
