"""Native C++ batch-prep kernels vs the pure-Python fallback."""
import numpy as np
import pytest

import intellillm_tpu.native as native


def _python_fallback(monkeypatch):
    """Force the Python path regardless of the built library."""
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_tried", True)


def test_native_library_builds():
    assert native.is_available(), (
        "g++ is in the image; the native batch-prep library must build")


@pytest.mark.parametrize("seed", [0, 1])
def test_decode_batch_native_matches_python(monkeypatch, seed):
    rng = np.random.default_rng(seed)
    n, padded_n, width = 5, 8, 6
    tables = [list(rng.integers(0, 100, size=rng.integers(1, width + 1)))
              for _ in range(n)]
    tokens = list(rng.integers(0, 1000, size=n))
    poss = list(rng.integers(0, 100, size=n))
    ctxs = [p + 1 for p in poss]

    got = native.build_decode_batch(tables, tokens, poss, ctxs, padded_n,
                                    width)
    _python_fallback(monkeypatch)
    ref = native.build_decode_batch(tables, tokens, poss, ctxs, padded_n,
                                    width)
    for g, r in zip(got, ref):
        np.testing.assert_array_equal(g, r)


@pytest.mark.parametrize("window_blocks,prefix_len", [
    (None, 0), (None, 16), (2, 0),
])
def test_prompt_slots_native_matches_python(monkeypatch, window_blocks,
                                            prefix_len):
    rng = np.random.default_rng(3)
    block_size, seq_len = 16, 70
    table = list(rng.integers(0, 100, size=8))
    got = native.build_prompt_slots(table, prefix_len, seq_len, block_size,
                                    window_blocks, -1)
    _python_fallback(monkeypatch)
    ref = native.build_prompt_slots(table, prefix_len, seq_len, block_size,
                                    window_blocks, -1)
    np.testing.assert_array_equal(got, ref)


def test_prompt_slots_semantics():
    """Direct check of the slot formula and window suppression."""
    table = [7, 3, 9]
    slots = native.build_prompt_slots(table, 0, 40, 16, None, -1)
    assert slots[0] == 7 * 16 + 0
    assert slots[17] == 3 * 16 + 1
    assert slots[39] == 9 * 16 + 7
    # Window of 1 block over 40 tokens: everything before the last 16
    # tokens is suppressed; the rest wraps modulo 1 block.
    slots = native.build_prompt_slots(table, 0, 40, 16, 1, -1)
    assert (slots[:24] == -1).all()
    assert slots[24] == 7 * 16 + 8    # token 24 → logical 1 % 1 = 0
