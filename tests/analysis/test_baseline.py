"""Baseline round-trip and the shrink-only gate semantics."""
import json

import pytest

from intellillm_tpu.analysis.baseline import (load_baseline, save_baseline,
                                              split_baselined)


def test_round_trip_grandfathers_violations(tmp_path, run_mini):
    found = run_mini(rule_ids=["host-sync"])
    assert len(found.violations) == 2

    baseline = tmp_path / "baseline.json"
    save_baseline(baseline, found.violations)

    gated = run_mini(rule_ids=["host-sync"], baseline_path=baseline,
                     use_baseline=True)
    assert gated.ok
    assert gated.violations == []
    assert len(gated.baselined) == 2
    assert gated.stale_baseline == []


def test_stale_entry_fails_the_gate(tmp_path, run_mini):
    found = run_mini(rule_ids=["host-sync"])
    baseline = tmp_path / "baseline.json"
    save_baseline(baseline, found.violations)

    # Simulate paying off one debt: its entry is now stale.
    data = json.loads(baseline.read_text())
    paid, data["entries"] = data["entries"][0], data["entries"][1:]
    extinct = dict(paid)
    extinct["context"] = "this_line_no_longer_exists()"
    data["entries"].append(extinct)
    baseline.write_text(json.dumps(data))

    gated = run_mini(rule_ids=["host-sync"], baseline_path=baseline,
                     use_baseline=True)
    assert not gated.ok
    # The un-baselined violation resurfaces AND the stale entry fails.
    assert len(gated.violations) == 1
    assert gated.stale_baseline == [extinct]


def test_fingerprint_survives_line_drift(run_mini):
    """Fingerprints key on the offending text, not the line number."""
    found = run_mini(rule_ids=["host-sync"])
    entries = [{"rule": v.rule, "path": v.path, "context": v.context}
               for v in found.violations]
    shifted = [v for v in found.violations]
    for violation in shifted:
        violation.line += 40  # unrelated edits moved the file around
    active, baselined, stale = split_baselined(shifted, entries)
    assert active == []
    assert len(baselined) == 2
    assert stale == []


def test_missing_baseline_file_is_empty(tmp_path):
    assert load_baseline(tmp_path / "nope.json") == []


def test_malformed_entry_rejected(tmp_path):
    path = tmp_path / "baseline.json"
    path.write_text(json.dumps({"entries": [{"rule": "host-sync"}]}))
    with pytest.raises(ValueError, match="malformed"):
        load_baseline(path)
