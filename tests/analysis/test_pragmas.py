"""Pragma parsing and suppression semantics."""
import textwrap

from intellillm_tpu.analysis.core import parse_pragmas


def test_trailing_pragma_parsed():
    pragmas = parse_pragmas(
        "x = 1  # lint: allow(host-sync) reason=intentional fetch\n")
    assert list(pragmas) == [1]
    pragma = pragmas[1]
    assert pragma.rules == ("host-sync", )
    assert pragma.reason == "intentional fetch"
    assert pragma.valid


def test_multi_rule_pragma():
    pragmas = parse_pragmas(
        "# lint: allow(host-sync, async-blocking) reason=both waived\n"
        "x = 1\n")
    assert pragmas[1].rules == ("host-sync", "async-blocking")


def test_missing_reason_is_invalid():
    pragmas = parse_pragmas("x = 1  # lint: allow(host-sync)\n")
    assert not pragmas[1].valid


def test_docstring_mention_is_not_a_pragma():
    text = textwrap.dedent('''
        def helper():
            """Write `# lint: allow(host-sync) reason=...` to waive."""
            return 1
    ''')
    assert parse_pragmas(text) == {}


def test_fallback_scan_for_unparseable_files():
    text = "def broken(:\n    x = 1  # lint: allow(host-sync) reason=still seen\n"
    pragmas = parse_pragmas(text)
    assert list(pragmas) == [2]
    assert pragmas[2].valid


def test_same_line_and_preceding_line_suppress(tmp_path, mini_settings):
    from intellillm_tpu.analysis import run_analysis

    target = tmp_path / "pkg"
    target.mkdir()
    (target / "runner.py").write_text(
        "import jax\n"
        "\n"
        "\n"
        "class Runner:\n"
        "\n"
        "    def execute_model(self, out):\n"
        "        jax.block_until_ready(out)  # lint: allow(host-sync) reason=same line\n"
        "        # lint: allow(host-sync) reason=preceding line\n"
        "        jax.block_until_ready(out)\n"
        "        jax.block_until_ready(out)\n",
        encoding="utf-8")
    mini_settings.repo_root = tmp_path
    result = run_analysis(repo_root=tmp_path, targets=("pkg", ),
                          rule_ids=["host-sync"], settings=mini_settings,
                          use_baseline=False)
    assert [v.line for v in result.suppressed] == [7, 9]
    assert [v.line for v in result.violations] == [10]
