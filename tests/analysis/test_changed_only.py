"""--changed-only semantics: scope the report, never fabricate findings."""
import intellillm_tpu.analysis.engine as engine_mod
from intellillm_tpu.analysis.engine import git_changed_files


def test_report_scoped_to_changed_files(run_mini, monkeypatch):
    monkeypatch.setattr(engine_mod, "git_changed_files",
                        lambda root, base=None: {"pkg/server.py"})
    result = run_mini(changed_only=True)
    assert result.files_scanned == 1
    assert {v.path for v in result.violations} == {"pkg/server.py"}
    # async-blocking + the 2 handle growths + sync_helper growth.
    assert len(result.violations) == 4
    # The cross-file doc rules still ran over the whole tree, but their
    # findings for unchanged files are scoped out of this report.
    assert not any(v.rule in ("flag-docs", "docs-metrics")
                   for v in result.violations)


def test_no_changes_means_clean(run_mini, monkeypatch):
    monkeypatch.setattr(engine_mod, "git_changed_files",
                        lambda root, base=None: set())
    result = run_mini(changed_only=True)
    assert result.ok
    assert result.files_scanned == 0


def test_stale_entries_only_judged_for_scanned_files(run_mini, monkeypatch,
                                                     tmp_path):
    """A partial scan must not flag baseline entries for files it never
    looked at."""
    import json

    baseline = tmp_path / "baseline.json"
    baseline.write_text(json.dumps({"entries": [
        {"rule": "host-sync", "path": "pkg/runner.py",
         "context": "jax.block_until_ready(out)"},
    ]}))
    monkeypatch.setattr(engine_mod, "git_changed_files",
                        lambda root, base=None: {"pkg/server.py"})
    result = run_mini(changed_only=True, baseline_path=baseline,
                      use_baseline=True)
    assert result.stale_baseline == []

    # A full scan with the same baseline does see the entry matched.
    full = run_mini(baseline_path=baseline, use_baseline=True)
    assert full.stale_baseline == []
    assert len(full.baselined) == 1


def test_git_changed_files_returns_relative_paths():
    """Smoke against the real repo: paths are repo-relative posix."""
    from intellillm_tpu.analysis.engine import repo_root_from_here

    changed = git_changed_files(repo_root_from_here())
    assert isinstance(changed, set)
    for path in changed:
        assert not path.startswith("/")
