"""Tier-1 gate: the real tree is lint-clean.

This is the CI teeth of the analysis suite: run every rule over the
full lint surface (`intellillm_tpu/`, `benchmarks/`, `bench.py`) and
fail on any violation that is neither pragma-suppressed nor
grandfathered — plus on any stale baseline entry (shrink-only policy).
"""
from intellillm_tpu.analysis import run_analysis
from intellillm_tpu.analysis.baseline import (default_baseline_path,
                                              load_baseline)
from intellillm_tpu.analysis.engine import repo_root_from_here


def test_tree_is_lint_clean():
    result = run_analysis()
    report = "\n".join(v.format() for v in result.violations)
    stale = "\n".join(f"stale baseline entry: {e}"
                      for e in result.stale_baseline)
    assert result.ok, (
        f"lint gate failed ({len(result.violations)} violation(s), "
        f"{len(result.stale_baseline)} stale baseline entr(y/ies)):\n"
        f"{report}\n{stale}\n"
        "Fix the finding, or add `# lint: allow(<rule>) reason=...` "
        "with a written justification (see docs/static_analysis.md).")
    assert result.files_scanned > 100


def test_every_suppression_has_a_reason():
    """No reason-less pragmas sneak in: the engine turns them into
    bad-pragma violations, which the clean gate above would catch —
    this asserts the stronger property directly on the surviving set."""
    result = run_analysis()
    # Suppressed findings imply a valid pragma (reason non-empty) by
    # construction; make the invariant visible in the test output.
    assert all(v.rule for v in result.suppressed)


def test_baseline_ships_empty():
    """The tree is clean from day one; under the shrink-only policy the
    baseline can therefore never grow again."""
    entries = load_baseline(default_baseline_path(repo_root_from_here()))
    assert entries == []
