"""docs-metrics fixture: documented, undocumented, and waived metrics.

The docs-metrics rule scans `<repo_root>/intellillm_tpu` for metric
literals, so the mini repo mirrors that layout.
"""

STEP_SECONDS = "intellillm_fixture_step_seconds"
ORPHAN = "intellillm_fixture_orphan_total"
# lint: allow(docs-metrics) reason=fixture: internal series, deliberately undocumented
HIDDEN = "intellillm_fixture_hidden_total"
