"""Designated metrics module: prefixed family + reset hook = clean."""
from prometheus_client import REGISTRY, Counter

FIXTURE_REQS = Counter("intellillm_fixture_requests_total",
                       "fixture requests")


def reset_for_testing():
    REGISTRY.unregister(FIXTURE_REQS)
