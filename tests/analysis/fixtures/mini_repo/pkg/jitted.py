"""recompile-hazard fixture: trace-unsafe bodies and a non-static shape arg."""
import functools
import time

import jax


@jax.jit
def decode_step(tokens, num_steps):
    t = time.time()
    print("trace-time only", t)
    return tokens


@functools.partial(jax.jit, static_argnames=("num_steps",))
def decode_step_ok(tokens, num_steps):
    return tokens


@jax.jit
def seeded(tokens):
    # lint: allow(recompile-hazard) reason=fixture: trace-time constant is intended here
    t0 = time.monotonic()
    return tokens, t0


def _inner_fn(x, top_k):
    return x


_jit_inner = jax.jit(_inner_fn)
