"""metric-hygiene fixture: collectors outside the designated modules."""
from prometheus_client import Counter, Gauge

ROGUE = Counter("rogue_total", "unprefixed, wrong module")
# lint: allow(metric-hygiene) reason=fixture: scratch gauge for a local experiment
SCRATCH = Gauge("intellillm_fixture_scratch", "suppressed placement")
