"""flag-docs env-var fixture: one documented, one not."""
import os

POLL = os.environ.get("INTELLILLM_FIXTURE_POLL_SEC", "5")
DEBUG = os.environ.get("INTELLILLM_FIXTURE_HIDDEN", "0")
