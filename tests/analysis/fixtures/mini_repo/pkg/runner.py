"""host-sync fixture: a step loop with stray syncs and one blessed fetch."""
import jax
import numpy as np


class Runner:

    def execute_model(self, batch):
        out = self._dispatch(batch)
        jax.block_until_ready(out)
        flag = out.done.item()
        return out, flag

    def _finalize(self, packed):
        # lint: allow(host-sync) reason=fixture: the designed single fetch point
        host = np.asarray(packed)
        return host

    def _dispatch(self, batch):
        return batch

    def cold_path(self, batch):
        return np.asarray(batch)
