"""async-blocking + unbounded-growth fixture: a per-request server path."""
import time

REQUEST_LOG = []
_CACHE = {}
RECENT = None  # not a container: never flagged


async def handle(request):
    time.sleep(0.1)
    REQUEST_LOG.append(request)
    _CACHE[request.id] = request
    return request


async def shutdown(proc):
    # lint: allow(async-blocking) reason=fixture: shutdown path, loop is draining anyway
    proc.wait(timeout=5)


async def audit(request):
    # lint: allow(unbounded-growth) reason=fixture: flushed by the harness every batch
    REQUEST_LOG.append(request)


def sync_helper(request):
    REQUEST_LOG.append(request)
