"""unlocked-shared-state fixture: a poller thread sharing state."""
import threading


class Poller:

    def __init__(self):
        self._lock = threading.Lock()
        self._samples = []
        self._last = None
        self._errors = 0

    def start(self):
        thread = threading.Thread(target=self._loop, daemon=True)
        thread.start()

    def _loop(self):
        while True:
            self._last = self._read()
            with self._lock:
                self._samples.append(self._last)
            # lint: allow(unlocked-shared-state) reason=fixture: int bump tolerates torn reads
            self._errors += 1

    def _read(self):
        return 1

    def snapshot(self):
        with self._lock:
            return list(self._samples), self._last, self._errors
