"""flag-docs fixture: seed, documented, undocumented, and waived flags."""
import argparse


def build_parser():
    parser = argparse.ArgumentParser()
    parser.add_argument("--model")
    parser.add_argument("--fixture-documented")
    parser.add_argument("--fixture-undocumented")
    # lint: allow(flag-docs) reason=fixture: internal debug flag, deliberately undocumented
    parser.add_argument("--fixture-internal")
    return parser
