def broken(:
    return
