"""Engine fixture: invalid pragmas are themselves violations."""

VALUE = 1  # lint: allow(host-sync)
OTHER = 2  # lint: allow(not-a-rule) reason=typo in the rule id
