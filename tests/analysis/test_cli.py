"""CLI surface: python -m intellillm_tpu.tools.lint."""
import json

from intellillm_tpu.tools.lint import main


def test_list_rules(capsys):
    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule_id in ("host-sync", "recompile-hazard", "async-blocking",
                    "unlocked-shared-state", "metric-hygiene",
                    "unbounded-growth", "flag-docs", "docs-metrics",
                    "bad-pragma", "parse-error"):
        assert rule_id in out, rule_id


def test_tree_exits_zero_human(capsys):
    assert main([]) == 0
    assert "clean:" in capsys.readouterr().out


def test_tree_exits_zero_json(capsys):
    assert main(["--format", "json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["ok"] is True
    assert payload["violations"] == []
    assert payload["stale_baseline"] == []
    assert payload["files_scanned"] > 100


def test_unknown_rule_id_is_a_usage_error(capsys):
    assert main(["--rules", "not-a-rule"]) == 2
    assert "not-a-rule" in capsys.readouterr().err


def test_rule_subset_runs(capsys):
    assert main(["--rules", "host-sync", "intellillm_tpu/worker"]) == 0
    assert "clean:" in capsys.readouterr().out
