"""Per-rule fixture tests: every rule has at least one true positive
and one pragma-suppressed case in the miniature repo."""


def _by_rule(violations, rule):
    return [v for v in violations if v.rule == rule]


def _lines(violations):
    return sorted((v.path, v.line) for v in violations)


class TestHostSync:

    def test_true_positives(self, run_mini):
        result = run_mini(rule_ids=["host-sync"])
        assert _lines(result.violations) == [
            ("pkg/runner.py", 10),  # jax.block_until_ready
            ("pkg/runner.py", 11),  # .item()
        ]
        assert "block_until_ready" in result.violations[0].message
        assert "Runner.execute_model" in result.violations[0].message

    def test_pragma_suppressed_fetch(self, run_mini):
        result = run_mini(rule_ids=["host-sync"])
        assert _lines(result.suppressed) == [("pkg/runner.py", 16)]

    def test_non_hot_path_is_clean(self, run_mini):
        result = run_mini(rule_ids=["host-sync"])
        assert not any("cold_path" in v.message for v in result.violations)


class TestRecompileHazard:

    def test_true_positives(self, run_mini):
        result = run_mini(rule_ids=["recompile-hazard"])
        lines = _lines(result.violations)
        # decode_step: non-static num_steps (def line), time.time, print.
        assert ("pkg/jitted.py", 9) in lines
        assert ("pkg/jitted.py", 10) in lines
        assert ("pkg/jitted.py", 11) in lines
        # _inner_fn jitted at a wrap site: top_k not static.
        assert ("pkg/jitted.py", 27) in lines
        assert len(lines) == 4

    def test_static_argnames_accepted(self, run_mini):
        result = run_mini(rule_ids=["recompile-hazard"])
        assert not any("decode_step_ok" in v.message
                       for v in result.violations + result.suppressed)

    def test_pragma_suppressed(self, run_mini):
        result = run_mini(rule_ids=["recompile-hazard"])
        assert _lines(result.suppressed) == [("pkg/jitted.py", 23)]
        assert "time.monotonic" in result.suppressed[0].message


class TestAsyncBlocking:

    def test_true_positive(self, run_mini):
        result = run_mini(rule_ids=["async-blocking"])
        assert _lines(result.violations) == [("pkg/server.py", 10)]
        assert "time.sleep" in result.violations[0].message
        assert "async def handle" in result.violations[0].message

    def test_pragma_suppressed_wait(self, run_mini):
        result = run_mini(rule_ids=["async-blocking"])
        assert _lines(result.suppressed) == [("pkg/server.py", 18)]
        assert ".wait" in result.suppressed[0].message


class TestUnlockedSharedState:

    def test_true_positive(self, run_mini):
        result = run_mini(rule_ids=["unlocked-shared-state"])
        assert _lines(result.violations) == [("pkg/telemetry.py", 19)]
        violation = result.violations[0]
        assert "_last" in violation.message
        assert "Poller._loop" in violation.message
        assert "Poller.snapshot" in violation.message

    def test_locked_write_is_clean(self, run_mini):
        result = run_mini(rule_ids=["unlocked-shared-state"])
        assert not any("_samples" in v.message for v in result.violations)

    def test_pragma_suppressed(self, run_mini):
        result = run_mini(rule_ids=["unlocked-shared-state"])
        assert _lines(result.suppressed) == [("pkg/telemetry.py", 23)]


class TestMetricHygiene:

    def test_true_positives(self, run_mini):
        result = run_mini(rule_ids=["metric-hygiene"])
        lines = _lines(result.violations)
        # rogue.py line 4: placement + prefix; line 1: no reset hook.
        assert lines == [("pkg/rogue.py", 1), ("pkg/rogue.py", 4),
                         ("pkg/rogue.py", 4)]
        messages = " | ".join(v.message for v in result.violations)
        assert "reset_for_testing" in messages
        assert "intellillm_" in messages
        assert "outside" in messages

    def test_designated_module_is_clean(self, run_mini):
        result = run_mini(rule_ids=["metric-hygiene"])
        assert not any(v.path.startswith("pkg/metrics/")
                       for v in result.violations + result.suppressed)

    def test_pragma_suppressed_placement(self, run_mini):
        result = run_mini(rule_ids=["metric-hygiene"])
        assert _lines(result.suppressed) == [("pkg/rogue.py", 6)]


class TestUnboundedGrowth:

    def test_true_positives(self, run_mini):
        result = run_mini(rule_ids=["unbounded-growth"])
        assert _lines(result.violations) == [
            ("pkg/server.py", 11),  # REQUEST_LOG.append in handle
            ("pkg/server.py", 12),  # _CACHE[...] = in handle
            ("pkg/server.py", 27),  # REQUEST_LOG.append in sync_helper
        ]

    def test_pragma_suppressed(self, run_mini):
        result = run_mini(rule_ids=["unbounded-growth"])
        assert _lines(result.suppressed) == [("pkg/server.py", 23)]


class TestFlagDocs:

    def test_true_positives(self, run_mini):
        result = run_mini(rule_ids=["flag-docs"])
        lines = _lines(result.violations)
        assert ("pkg/flags.py", 9) in lines        # --fixture-undocumented
        assert ("pkg/obs/envs.py", 5) in lines     # INTELLILLM_FIXTURE_HIDDEN
        assert len(lines) == 2

    def test_seed_and_documented_flags_skipped(self, run_mini):
        result = run_mini(rule_ids=["flag-docs"])
        everything = result.violations + result.suppressed
        assert not any("--model" in v.message for v in everything)
        assert not any("--fixture-documented" in v.message
                       for v in everything)

    def test_pragma_suppressed(self, run_mini):
        result = run_mini(rule_ids=["flag-docs"])
        assert _lines(result.suppressed) == [("pkg/flags.py", 11)]
        assert "--fixture-internal" in result.suppressed[0].message


class TestDocsMetrics:

    def test_true_positives(self, run_mini):
        result = run_mini(rule_ids=["docs-metrics"])
        by_path = {v.path: v for v in result.violations}
        orphan = by_path["intellillm_tpu/metrics_src.py"]
        assert orphan.line == 8
        assert "intellillm_fixture_orphan_total" in orphan.message
        ghost = by_path["docs/ops.md"]
        assert "intellillm_fixture_ghost_total" in ghost.message
        assert len(result.violations) == 2

    def test_pragma_suppressed(self, run_mini):
        result = run_mini(rule_ids=["docs-metrics"])
        assert _lines(result.suppressed) == [
            ("intellillm_tpu/metrics_src.py", 10)]


class TestEngineChecks:

    def test_bad_pragmas_and_parse_errors(self, run_mini):
        result = run_mini(targets=("engine_cases", ))
        bad = _by_rule(result.violations, "bad-pragma")
        assert _lines(bad) == [("engine_cases/bad_pragma.py", 3),
                               ("engine_cases/bad_pragma.py", 4)]
        assert "no reason=" in bad[0].message
        assert "not-a-rule" in bad[1].message
        parse = _by_rule(result.violations, "parse-error")
        assert _lines(parse) == [("engine_cases/broken.py", 1)]

    def test_full_mini_repo_totals(self, run_mini):
        """Whole-tree aggregate: the per-rule counts add up, nothing
        double-reports, and every suppression carries a reason."""
        result = run_mini()
        per_rule = {}
        for violation in result.violations:
            per_rule[violation.rule] = per_rule.get(violation.rule, 0) + 1
        assert per_rule == {
            "host-sync": 2,
            "recompile-hazard": 4,
            "async-blocking": 1,
            "unlocked-shared-state": 1,
            "metric-hygiene": 3,
            "unbounded-growth": 3,
            "flag-docs": 2,
            "docs-metrics": 2,
        }
        assert len(result.suppressed) == 8


class TestBucketAxisGuard:
    """recompile-hazard's bucket-axis pin: a module listed in
    Settings.bucket_axes may only define the dispatch-bucket axes named
    there — any new `*_buckets` attribute/global is a fresh jit dispatch
    axis (one executable per value) and fails the lint."""

    def _run(self, tmp_path, source):
        import pathlib

        from intellillm_tpu.analysis import Settings, run_analysis

        pkg = tmp_path / "pkg"
        pkg.mkdir(exist_ok=True)
        (pkg / "__init__.py").write_text("")
        (pkg / "runner.py").write_text(source)
        settings = Settings(
            repo_root=pathlib.Path(tmp_path),
            hot_paths={}, extra_traced={},
            bucket_axes={"pkg/runner.py": ("mixed_token_buckets", )})
        return run_analysis(repo_root=pathlib.Path(tmp_path),
                            targets=("pkg", ),
                            rule_ids=["recompile-hazard"],
                            settings=settings, use_baseline=False)

    def test_new_axis_flagged(self, tmp_path):
        result = self._run(tmp_path, (
            "class Runner:\n"
            "    def __init__(self):\n"
            "        self.mixed_token_buckets = [16, 32]\n"
            "        self.batch_buckets = [1, 2, 4]\n"))
        assert len(result.violations) == 1
        violation = result.violations[0]
        assert violation.rule == "recompile-hazard"
        assert ("pkg/runner.py", 4) == (violation.path, violation.line)
        assert "batch_buckets" in violation.message
        assert "mixed_token_buckets" in violation.message

    def test_pinned_axis_clean(self, tmp_path):
        result = self._run(tmp_path, (
            "class Runner:\n"
            "    def __init__(self):\n"
            "        self.mixed_token_buckets = [16, 32]\n"
            "        top = self.mixed_token_buckets[-1]\n"
            "        assert top\n"))
        assert result.violations == []

    def test_pragma_suppresses_with_reason(self, tmp_path):
        result = self._run(tmp_path, (
            "class Runner:\n"
            "    def __init__(self):\n"
            "        self.mixed_token_buckets = [16, 32]\n"
            "        # lint: allow(recompile-hazard) reason=fixture\n"
            "        self.len_buckets = [8]\n"))
        assert result.violations == []
        assert len(result.suppressed) == 1

    def test_real_repo_pin_present(self):
        """The default Settings must keep model_runner.py pinned to the
        mixed family — deleting the pin would silently disable the
        guard this test exists for."""
        from intellillm_tpu.analysis.core import DEFAULT_BUCKET_AXES
        assert DEFAULT_BUCKET_AXES[
            "intellillm_tpu/worker/model_runner.py"] == (
                "mixed_token_buckets", )
