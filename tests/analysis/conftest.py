"""Shared fixtures: a Settings pointing at the miniature fixture repo.

`fixtures/mini_repo/` is a self-contained tree with at least one true
positive and one pragma-suppressed case per rule; every repo-specific
knob in `Settings` is overridden to match its layout, which is exactly
how the rules stay testable without scanning the real package.
"""
import pathlib

import pytest

from intellillm_tpu.analysis import Settings, run_analysis

MINI_ROOT = pathlib.Path(__file__).parent / "fixtures" / "mini_repo"
MINI_TARGETS = ("pkg", "intellillm_tpu")


def make_mini_settings() -> Settings:
    return Settings(
        repo_root=MINI_ROOT,
        hot_paths={"pkg/runner.py": ("Runner.execute_model",
                                     "Runner._finalize")},
        extra_traced={},
        metrics_modules=("pkg/metrics/*.py", ),
        request_path_globs=("pkg/server.py", ),
        flag_sources=("pkg/flags.py", ),
        seed_flags=frozenset({"--model"}),
        doc_files=("docs/ops.md", ),
        metrics_doc="docs/ops.md",
        env_var_dirs=("pkg/obs", ),
        non_metrics=frozenset(),
    )


@pytest.fixture
def mini_settings() -> Settings:
    return make_mini_settings()


@pytest.fixture
def run_mini(mini_settings):
    def _run(rule_ids=None, targets=MINI_TARGETS, **kwargs):
        kwargs.setdefault("use_baseline", False)
        return run_analysis(repo_root=MINI_ROOT, targets=targets,
                            rule_ids=rule_ids, settings=mini_settings,
                            **kwargs)

    return _run
