"""Chunked-prefill scheduler unit tests (CPU-only, no model).

Covers the acceptance properties of the mixed decode+prefill pass:
decode-first admission (decodes are never starved by prefill chunks),
the converse starvation guarantee (prompts progress even when decodes
fill the budget — padding headroom first, then a one-step decode
deferral), per-step token budget respected by chunk sizing, and
`num_computed_tokens` surviving preemption — recompute resets it, swap
preserves it. Plus a golden step-trace test pinning the exact chunk
schedule, the --disable-chunked-prefill whole-prompt-chunk mode, and
the flat-batch padding admission accounting.
"""
import pytest

from intellillm_tpu.config import CacheConfig, SchedulerConfig
from intellillm_tpu.core.scheduler import PreemptionMode, Scheduler
from intellillm_tpu.sampling_params import SamplingParams
from intellillm_tpu.sequence import Sequence, SequenceGroup, SequenceStatus


def make_chunked_scheduler(num_blocks=64, block_size=4, max_num_seqs=8,
                           budget=8, max_model_len=64, max_paddings=256,
                           num_cpu_blocks=32):
    cache_config = CacheConfig(block_size=block_size, swap_space_gib=0.001)
    cache_config.num_device_blocks = num_blocks
    cache_config.num_cpu_blocks = num_cpu_blocks
    scheduler_config = SchedulerConfig(
        max_num_batched_tokens=budget,
        max_num_seqs=max_num_seqs,
        max_model_len=max_model_len,
        max_paddings=max_paddings,
        enable_chunked_prefill=True)
    return Scheduler(scheduler_config, cache_config)


def add_request(scheduler, rid, prompt_len, block_size=4, **sp_kwargs):
    seq = Sequence(int(rid), "x", list(range(prompt_len)), block_size)
    sp = SamplingParams(**sp_kwargs) if sp_kwargs else SamplingParams(
        temperature=0.0, max_tokens=16)
    group = SequenceGroup(rid, [seq], sp, arrival_time=float(rid))
    scheduler.add_seq_group(group)
    return group, seq


def append_token(group):
    for seq in group.get_seqs(SequenceStatus.RUNNING):
        seq.append_token_id(1, {1: 0.0})


def run_step(scheduler):
    """One schedule() pass plus the host-side effects of a model step:
    every final-chunk / decode group appends one token."""
    metas, out = scheduler.schedule()
    chunks = out.chunked_prefills or {}
    for meta in metas:
        chunk = chunks.get(meta.request_id)
        if chunk is not None and not chunk[2]:
            continue  # mid-prefill: no token emitted
        for sd in meta.seq_data.values():
            sd.append_token_id(1, 0.0)
    return metas, out


def test_prompt_splits_across_steps_within_budget():
    s = make_chunked_scheduler(budget=8)
    _, seq = add_request(s, "0", 20)
    metas, out = s.schedule()
    assert out.is_mixed
    assert out.chunked_prefills["0"] == (0, 8, False)
    assert seq.data.get_num_computed_tokens() == 8
    assert not seq.data.prefill_complete
    # Mid-prefill metadata carries the chunk window.
    assert metas[0].is_prompt and metas[0].token_chunk_size == 8
    assert metas[0].num_computed_tokens == 0

    _, out = s.schedule()
    assert out.chunked_prefills["0"] == (8, 8, False)
    _, out = s.schedule()
    assert out.chunked_prefills["0"] == (16, 4, True)
    assert seq.data.prefill_complete
    assert seq.data.get_num_computed_tokens() == 20


def test_decodes_never_starved_and_budget_respected():
    """Steady decode stream + a long prompt arriving late: every step with
    runnable decodes must schedule ALL of them, and decode rows + chunk
    tokens must never exceed the budget."""
    s = make_chunked_scheduler(budget=8, num_blocks=64)
    decode_groups = []
    for i in range(4):
        g, _ = add_request(s, str(i), 4)
        decode_groups.append(g)
    # Admit + fully prefill the short prompts (budget 8 → two at a time,
    # then a split tail for the last one).
    for _ in range(3):
        run_step(s)
    assert all(g.get_seqs()[0].data.prefill_complete for g in decode_groups)
    g_long, seq_long = add_request(s, "9", 30)

    seen_chunks = []
    for _ in range(6):
        metas, out = run_step(s)
        assert out.is_mixed
        scheduled_ids = {m.request_id for m in metas}
        # Decode-first: every live decode group is in the step.
        for g in decode_groups:
            assert g.request_id in scheduled_ids, (
                f"decode group {g.request_id} starved by prefill chunks")
        assert (out.num_mixed_decode_tokens + out.num_prefill_tokens
                <= s.scheduler_config.max_num_batched_tokens)
        assert out.num_mixed_decode_tokens == 4
        chunk = (out.chunked_prefills or {}).get("9")
        if chunk is not None:
            seen_chunks.append(chunk)
        if seq_long.data.prefill_complete:
            break
    # 30 tokens at 4 tokens/step of slack → 8 chunks; we ran 6 steps, so
    # progress must be strictly monotone and budget-shaped.
    assert seen_chunks, "long prompt never got a chunk"
    assert all(size <= 4 for _, size, _ in seen_chunks)
    starts = [start for start, _, _ in seen_chunks]
    assert starts == sorted(starts)
    assert starts[0] == 0


def test_golden_chunk_trace():
    """Pin the exact mixed-step schedule for a fixed arrival pattern —
    catches silent regressions in admission order or chunk sizing."""
    s = make_chunked_scheduler(budget=8)
    add_request(s, "0", 10)
    add_request(s, "1", 7)
    trace = []
    for _ in range(4):
        metas, out = run_step(s)
        trace.append((sorted((rid, c) for rid, c in
                             (out.chunked_prefills or {}).items()),
                      out.num_mixed_decode_tokens))
    assert trace == [
        # Step 1: "0" takes the full budget; "1" gets nothing.
        ([("0", (0, 8, False))], 0),
        # Step 2: "0" finishes (2 tokens), "1" starts into the slack (6).
        ([("0", (8, 2, True)), ("1", (0, 6, False))], 0),
        # Step 3: "0" decodes (1 row), "1" finishes its last token.
        ([("1", (6, 1, True))], 1),
        # Step 4: both decode, nothing left to prefill → legacy decode
        # pass (not mixed).
        ([], 0),
    ]


def test_chunked_off_admits_whole_prompt_chunks():
    """--disable-chunked-prefill mode: each prompt is admitted as ONE
    whole-prompt chunk (never split), executed through the same mixed
    dispatch; pure-decode steps carry no chunk metadata at all."""
    cache_config = CacheConfig(block_size=4, swap_space_gib=0.001)
    cache_config.num_device_blocks = 64
    cache_config.num_cpu_blocks = 8
    s = Scheduler(SchedulerConfig(
        max_num_batched_tokens=64, max_num_seqs=8, max_model_len=64,
        max_paddings=256), cache_config)
    add_request(s, "0", 20)
    add_request(s, "1", 5)
    metas, out = run_step(s)
    assert out.prompt_run
    assert out.chunked_prefills == {"0": (0, 20, True), "1": (0, 5, True)}
    assert out.num_prefill_tokens == 25
    assert all(m.is_prompt for m in metas)
    # Subsequent steps are plain decode passes: no chunk metadata.
    for _ in range(2):
        metas, out = run_step(s)
        assert not out.is_mixed
        assert out.chunked_prefills is None
        assert not out.prompt_run


def test_chunked_off_never_splits_a_prompt():
    """A prompt exceeding the per-step budget is deferred whole in
    --disable-chunked-prefill mode, not split across steps."""
    cache_config = CacheConfig(block_size=4, swap_space_gib=0.001)
    cache_config.num_device_blocks = 64
    cache_config.num_cpu_blocks = 8
    s = Scheduler(SchedulerConfig(
        max_num_batched_tokens=16, max_num_seqs=8, max_model_len=16,
        max_paddings=256), cache_config)
    add_request(s, "0", 12)
    add_request(s, "1", 12)   # 12 + 12 > 16 → deferred to its own step
    metas, out = run_step(s)
    assert [m.request_id for m in metas] == ["0"]
    assert out.chunked_prefills == {"0": (0, 12, True)}
    metas, out = run_step(s)
    assert [m.request_id for m in metas] == ["1"]
    assert out.chunked_prefills == {"1": (0, 12, True)}


def test_recompute_preemption_resets_computed_tokens():
    """A mid-prefill victim of recompute preemption loses its KV pages —
    its chunk progress must reset with them, and the re-admission must
    re-chunk from token 0."""
    s = make_chunked_scheduler(budget=8, num_blocks=11, block_size=4,
                               max_model_len=32)
    g0, seq0 = add_request(s, "0", 7)
    _, out = run_step(s)
    assert out.chunked_prefills["0"] == (0, 7, True)

    # g1's 32-token prompt fills the remaining pool exactly; g0's decode
    # growth eventually needs a block while g1 is still mid-chunk → g1
    # (lowest priority, single-seq) is recomputed away.
    g1, seq1 = add_request(s, "1", 32)
    admitted_mid = False
    completed_ever = False
    preempted = False
    for _ in range(12):
        run_step(s)
        completed_ever = completed_ever or seq1.data.prefill_complete
        if (seq1.status == SequenceStatus.RUNNING
                and seq1.data.get_num_computed_tokens() > 0):
            admitted_mid = True
        if admitted_mid and seq1.status == SequenceStatus.WAITING:
            preempted = True
            break
    assert preempted, "recompute preemption never hit the prefilling group"
    assert not completed_ever, (
        "construction error: prefill completed before preemption — "
        "this no longer tests the mid-chunk reset")
    assert seq1.data.get_num_computed_tokens() == 0
    assert not seq1.data.prefill_complete
    assert g1 in list(s.waiting)

    # Finish g0 → pool frees → g1 re-chunks from scratch.
    for seq in g0.get_seqs():
        seq.status = SequenceStatus.FINISHED_STOPPED
        s.free_seq(seq)
    s.free_finished_seq_groups()
    _, out = s.schedule()
    assert out.is_mixed
    assert out.chunked_prefills["1"][0] == 0


def test_swap_preemption_preserves_computed_tokens():
    """Forced SWAP of a mid-prefill group keeps its KV (and therefore its
    chunk progress); swap-in must resume chunking exactly where it
    stopped."""
    s = make_chunked_scheduler(budget=8, num_blocks=64)
    g, seq = add_request(s, "0", 20)
    _, out = s.schedule()
    assert out.chunked_prefills["0"] == (0, 8, False)
    assert seq.data.get_num_computed_tokens() == 8

    blocks_to_swap_out = {}
    s.running.remove(g)
    s._preempt(g, blocks_to_swap_out, PreemptionMode.SWAP)
    assert blocks_to_swap_out
    assert seq.status == SequenceStatus.SWAPPED
    assert seq.data.get_num_computed_tokens() == 8
    assert not seq.data.prefill_complete

    # The chunked pass owns swapped mid-prefill groups: swap-in, then
    # resume the chunk at start=8.
    _, out = s.schedule()
    assert out.is_mixed
    assert out.blocks_to_swap_in
    assert out.chunked_prefills["0"] == (8, 8, False)


def test_prompt_logprobs_prompts_chunk_like_any_other():
    """prompt_logprobs rides the mixed dispatch (per-chunk logits panels
    accumulate host-side): the prompt splits across steps under the
    budget like any other prompt."""
    s = make_chunked_scheduler(budget=8, max_model_len=32)
    _, seq = add_request(s, "0", 12, temperature=0.0, max_tokens=4,
                         prompt_logprobs=5)
    metas, out = s.schedule()
    assert out.is_mixed
    assert out.chunked_prefills["0"] == (0, 8, False)
    assert metas[0].token_chunk_size == 8
    _, out = s.schedule()
    assert out.chunked_prefills["0"] == (8, 4, True)
    assert seq.data.prefill_complete


def test_best_of_groups_share_mixed_steps():
    """best_of>1 groups fan out through the dispatch's multi-sample
    axis: their prompts chunk normally, and a new prompt chunks into
    the same mixed step their decodes run in."""
    s = make_chunked_scheduler(budget=16, max_num_seqs=8)
    g_multi, _ = add_request(s, "0", 4, temperature=0.8, best_of=2, n=2,
                             max_tokens=8)
    _, out = s.schedule()
    assert out.is_mixed
    assert out.chunked_prefills["0"] == (0, 4, True)
    for seq in g_multi.get_seqs(SequenceStatus.RUNNING):
        seq.append_token_id(1, {1: 0.0})
    add_request(s, "1", 10)
    metas, out = s.schedule()
    # One mixed step: the best_of group's decode rows plus the new
    # prompt's first chunk.
    assert out.is_mixed
    assert {m.request_id for m in metas} == {"0", "1"}
    assert out.num_mixed_decode_tokens >= 1
    assert out.chunked_prefills["1"][0] == 0


def test_whole_prompt_padding_budget_counts_flat_buckets():
    """--disable-chunked-prefill admission charges max_paddings against
    the mixed flat-batch token bucket the runner pads to, not the raw
    token count — and a lone prompt is always admitted (its bucket
    padding is intrinsic)."""
    cache_config = CacheConfig(block_size=4, swap_space_gib=0.001)
    cache_config.num_device_blocks = 64
    cache_config.num_cpu_blocks = 8
    s = Scheduler(SchedulerConfig(
        max_num_batched_tokens=128, max_num_seqs=8, max_model_len=64,
        max_paddings=48), cache_config)
    # Prompt 0: 60 tokens → flat bucket 64 → 4 paddings, admitted (and
    # would be even over the cap: lone-prompt exemption). Prompt 1:
    # 5 tokens → 65 total rows → flat bucket 128 → 63 paddings > 48 →
    # deferred to its own step.
    add_request(s, "0", 60)
    add_request(s, "1", 5)
    metas, out = s.schedule()
    assert out.prompt_run
    assert [m.request_id for m in metas] == ["0"]
    metas, out = s.schedule()
    assert [m.request_id for m in metas] == ["1"]


def test_lone_prompt_exempt_from_padding_cap():
    cache_config = CacheConfig(block_size=4, swap_space_gib=0.001)
    cache_config.num_device_blocks = 64
    cache_config.num_cpu_blocks = 8
    s = Scheduler(SchedulerConfig(
        max_num_batched_tokens=128, max_num_seqs=8, max_model_len=64,
        max_paddings=2), cache_config)
    add_request(s, "0", 33)  # bucket 64 → 31 paddings > cap, but lone
    metas, out = s.schedule()
    assert [m.request_id for m in metas] == ["0"]


def test_prompt_progress_via_padding_headroom_when_decodes_fill_budget():
    """Starvation corner, cheap half: decodes exactly consume the token
    budget but the flat bucket already pays for more rows — the waiting
    prompt's first chunk rides the padding headroom (free compute), and
    every decode still runs."""
    s = make_chunked_scheduler(budget=4, max_num_seqs=8, num_blocks=64)
    decode_groups = []
    for i in range(4):
        g, _ = add_request(s, str(i), 1)
        decode_groups.append(g)
    run_step(s)   # all four 1-token prompts prefill in one step
    assert all(g.get_seqs()[0].data.prefill_complete
               for g in decode_groups)

    _, seq = add_request(s, "9", 12)
    metas, out = run_step(s)
    assert out.is_mixed
    # All 4 decodes scheduled AND the prompt chunked: the chunk rows sit
    # in the bucket padding above the 4-token budget (smallest flat
    # bucket is 16 rows).
    assert out.num_mixed_decode_tokens == 4
    chunk = out.chunked_prefills.get("9")
    assert chunk is not None and chunk[0] == 0 and chunk[1] > 0
    assert {m.request_id for m in metas} == {"0", "1", "2", "3", "9"}


def test_prompt_progress_via_decode_deferral_at_bucket_boundary():
    """Starvation corner, hard half (the core/scheduler.py:266 fix):
    decode rows land exactly ON a flat bucket boundary, so there is no
    padding headroom — the scheduler defers ONE lowest-priority decode
    group for a single step so the waiting prompt still progresses, and
    the deferred group resumes decoding afterwards."""
    s = make_chunked_scheduler(budget=16, max_num_seqs=20, num_blocks=256,
                               max_model_len=64)
    decode_groups = []
    for i in range(16):
        g, _ = add_request(s, f"{i:02d}", 1)
        decode_groups.append(g)
    run_step(s)
    assert all(g.get_seqs()[0].data.prefill_complete
               for g in decode_groups)

    _, seq = add_request(s, "99", 6)
    tokens_before = {g.request_id: g.get_seqs()[0].data.get_len()
                     for g in decode_groups}
    steps = 0
    while not seq.data.prefill_complete:
        metas, out = run_step(s)
        steps += 1
        assert steps <= 12, "prompt starved: no prefill progress"
        assert out.is_mixed
        # Budget holds: scheduled decode rows + chunk tokens <= 16.
        assert (out.num_mixed_decode_tokens
                + out.num_prefill_tokens) <= 16
        # At most one decode group deferred per step.
        assert out.num_mixed_decode_tokens >= 15
        chunk = (out.chunked_prefills or {}).get("99")
        assert chunk is not None and chunk[1] >= 1, (
            "step made no prompt progress while decodes filled the "
            "budget")
    # Prefill completed; afterwards every decode group keeps decoding
    # (deferral was one step, not a starvation of its own).
    for _ in range(3):
        run_step(s)
    for g in decode_groups:
        assert g.get_seqs()[0].data.get_len() > tokens_before[g.request_id]
