"""Scheduler unit tests: admission budgets, preemption, policy ordering."""
import pytest

from intellillm_tpu.config import CacheConfig, SchedulerConfig
from intellillm_tpu.core.policy import PolicyFactory
from intellillm_tpu.core.scheduler import Scheduler
from intellillm_tpu.sampling_params import SamplingParams
from intellillm_tpu.sequence import Sequence, SequenceGroup, SequenceStatus


def make_scheduler(num_blocks=16, block_size=4, max_num_seqs=8,
                   policy="fcfs", num_decode_steps=1, max_model_len=64,
                   **config_kwargs):
    cache_config = CacheConfig(block_size=block_size, swap_space_gib=0.001)
    cache_config.num_device_blocks = num_blocks
    cache_config.num_cpu_blocks = 8
    scheduler_config = SchedulerConfig(
        max_num_batched_tokens=max(64, max_model_len),
        max_num_seqs=max_num_seqs,
        max_model_len=max_model_len,
        max_paddings=256,
        policy=policy,
        num_decode_steps=num_decode_steps,
        **config_kwargs)
    return Scheduler(scheduler_config, cache_config)


def add_request(scheduler, rid, prompt_len, block_size=4,
                predicted_len=None, **sp_kwargs):
    seq = Sequence(int(rid), "x", list(range(prompt_len)), block_size)
    sp = SamplingParams(**sp_kwargs) if sp_kwargs else SamplingParams(
        temperature=0.0, max_tokens=16)
    group = SequenceGroup(rid, [seq], sp, arrival_time=float(rid),
                          predicted_len=predicted_len)
    scheduler.add_seq_group(group)
    return group, seq


def append_token(group):
    for seq in group.get_seqs(SequenceStatus.RUNNING):
        seq.append_token_id(1, {1: 0.0})


def test_prefill_first_then_decode():
    s = make_scheduler()
    g1, _ = add_request(s, "0", 6)
    g2, _ = add_request(s, "1", 5)
    metas, out = s.schedule()
    assert out.prompt_run and len(metas) == 2
    append_token(g1)
    append_token(g2)
    metas, out = s.schedule()
    assert not out.prompt_run
    assert len(metas) == 2


def test_prompt_too_long_is_ignored():
    s = make_scheduler(max_model_len=8)
    g, seq = add_request(s, "0", 100)
    metas, out = s.schedule()
    assert not metas
    assert out.ignored_seq_groups == [g]
    assert seq.status == SequenceStatus.FINISHED_IGNORED


def test_admission_respects_max_num_seqs():
    s = make_scheduler(max_num_seqs=2, num_blocks=64)
    for i in range(4):
        add_request(s, str(i), 4)
    metas, out = s.schedule()
    assert len(metas) == 2
    assert len(s.waiting) == 2


def test_preemption_by_recompute_when_out_of_blocks():
    # 4 blocks of 4 tokens; two seqs with 8-token prompts fill everything.
    s = make_scheduler(num_blocks=4, block_size=4)
    g1, _ = add_request(s, "0", 8)
    g2, _ = add_request(s, "1", 8)
    metas, out = s.schedule()
    assert len(metas) == 2
    append_token(g1)
    append_token(g2)
    # Decode needs a new block per seq; none free → lowest-priority (g2,
    # arrived later) preempted by recompute back to waiting.
    metas, out = s.schedule()
    assert not out.prompt_run
    assert len(metas) == 1
    assert metas[0].request_id == "0"
    assert g2.get_seqs()[0].status == SequenceStatus.WAITING
    assert len(s.waiting) == 1


def test_sjf_policy_orders_waiting_by_predicted_len():
    s = make_scheduler(policy="sjf", max_num_seqs=1, num_blocks=64)
    add_request(s, "0", 4, predicted_len=500)
    g_short, _ = add_request(s, "1", 4, predicted_len=5)
    metas, out = s.schedule()
    assert [m.request_id for m in metas] == ["1"], (
        "SJF must admit the shortest predicted job first")


def test_fcfs_policy_priority():
    fcfs = PolicyFactory.get_policy("fcfs")
    g_old = SequenceGroup("a", [Sequence(0, "x", [1], 4)],
                          SamplingParams(), arrival_time=0.0)
    g_new = SequenceGroup("b", [Sequence(1, "x", [1], 4)],
                          SamplingParams(), arrival_time=10.0)
    order = fcfs.sort_by_priority(100.0, [g_new, g_old])
    assert [g.request_id for g in order] == ["a", "b"]


def test_multi_step_reserves_blocks():
    s = make_scheduler(num_blocks=16, block_size=4, num_decode_steps=8)
    g, seq = add_request(s, "0", 4)
    s.schedule()
    append_token(g)
    metas, out = s.schedule()
    assert out.num_decode_steps == 8
    # 4 prompt tokens + 1 output + 7 lookahead = 12 tokens → 3 blocks.
    assert len(s.block_manager.block_tables[seq.seq_id]) == 3


def test_beam_group_forces_single_step():
    s = make_scheduler(num_blocks=32, block_size=4, num_decode_steps=8)
    g, seq = add_request(s, "0", 4, use_beam_search=True, best_of=2,
                         temperature=0.0, max_tokens=8)
    s.schedule()
    append_token(g)
    metas, out = s.schedule()
    assert out.num_decode_steps == 1


def test_abort():
    s = make_scheduler()
    g, seq = add_request(s, "0", 4)
    s.schedule()
    s.abort_seq_group("0")
    assert not s.has_unfinished_seqs()
    assert seq.status == SequenceStatus.FINISHED_ABORTED
    assert s.block_manager.get_num_free_device_blocks() == 16


# --- length-predicted scheduling: calibration, aging, victim choice ----


def _group(rid, arrival, predicted_len):
    return SequenceGroup(rid, [Sequence(hash(rid) % 1000, "x", [1], 4)],
                         SamplingParams(), arrival_time=arrival,
                         predicted_len=predicted_len)


def test_sjf_remaining_unknown_lengths_sort_last_fcfs():
    """Unknown-length groups sort behind any predicted job and FCFS
    among themselves — the age term is a tiebreak, never dominant."""
    policy = PolicyFactory.get_policy("sjf_remaining")
    known = _group("k", arrival=90.0, predicted_len=10**6)
    unk_old = _group("a", arrival=0.0, predicted_len=None)
    unk_new = _group("b", arrival=80.0, predicted_len=None)
    order = policy.sort_by_priority(100.0, [unk_new, known, unk_old])
    assert [g.request_id for g in order] == ["k", "a", "b"]


def test_starvation_promotion_is_fcfs_above_sjf():
    policy = PolicyFactory.get_policy("sjf", starvation_s=5.0)
    long_oldest = _group("a", arrival=0.0, predicted_len=1000)
    long_older = _group("b", arrival=50.0, predicted_len=1000)
    short_fresh = _group("c", arrival=98.0, predicted_len=1)
    order = policy.sort_by_priority(
        100.0, [short_fresh, long_older, long_oldest])
    # Both long jobs waited past the deadline: promoted above the fresh
    # short job, ordered FCFS between themselves.
    assert [g.request_id for g in order] == ["a", "b", "c"]
    # Disabled (unset or 0) never promotes.
    for off in (PolicyFactory.get_policy("sjf"),
                PolicyFactory.get_policy("sjf", starvation_s=0)):
        assert off.starvation_s is None
        order = off.sort_by_priority(100.0, [long_oldest, short_fresh])
        assert [g.request_id for g in order] == ["c", "a"]


def test_starvation_deadline_bounds_queue_wait_in_scheduler():
    """An old long job must be admitted ahead of a stream of fresh
    short jobs once its wait exceeds --sjf-starvation-s."""
    import time
    s = make_scheduler(policy="sjf", max_num_seqs=1, num_blocks=64,
                       sjf_starvation_s=5.0)
    now = time.monotonic()
    g_long, _ = add_request(s, "0", 4, predicted_len=1000)
    g_long.arrival_time = now - 10.0  # waited past the deadline
    for rid in ("1", "2"):
        g, _ = add_request(s, rid, 4, predicted_len=1)
        g.arrival_time = now
    metas, _ = s.schedule()
    assert [m.request_id for m in metas] == ["0"], (
        "aged-out long job must be promoted over fresh short jobs")


def test_calibration_refresh_reorders_sjf_queue():
    """Golden ordering: a calibration update restamps a service-stamped
    in-flight prediction and flips the SJF admission order."""
    from intellillm_tpu.prediction import OnlineCalibrator

    s = make_scheduler(policy="sjf", max_num_seqs=1, num_blocks=64)
    g_stamped, _ = add_request(s, "0", 40, predicted_len=100)
    g_stamped.predicted_len_raw = 100         # stamped by the service
    g_oracle, _ = add_request(s, "1", 8, predicted_len=50)  # oracle len

    cal = OnlineCalibrator()
    cal.note_admission("warm", 40, 100)
    cal.observe("warm", 10)  # bucket 32-63 factor → 0.1, marked dirty
    assert cal.refresh_predictions(s.iter_seq_groups()) == 1
    assert g_stamped.predicted_len == 10
    assert g_oracle.predicted_len == 50  # oracle-supplied: never touched

    metas, _ = s.schedule()
    assert [m.request_id for m in metas] == ["0"], (
        "restamped prediction (10 < 50) must now win SJF admission")


def test_preemption_victim_is_most_predicted_remaining():
    """Under memory pressure the victim is the running group with the
    most predicted remaining work, not the priority-order tail."""
    # 7 blocks: three 8-token prompts use 6, and the conservative
    # can_append_slots check (2 free per appending seq) forces exactly
    # one preemption on the first decode step.
    s = make_scheduler(num_blocks=7, block_size=4)
    g1, _ = add_request(s, "0", 8, predicted_len=10)
    g2, _ = add_request(s, "1", 8, predicted_len=500)
    g3, _ = add_request(s, "2", 8, predicted_len=10)
    metas, _ = s.schedule()
    assert len(metas) == 3
    for g in (g1, g2, g3):
        append_token(g)
    metas, out = s.schedule()
    assert not out.prompt_run
    # Old behavior evicted the tail (g3); now the 500-token prediction
    # is evicted, freeing the most future block demand.
    assert [m.request_id for m in metas] == ["0", "2"]
    assert g2.get_seqs()[0].status == SequenceStatus.WAITING


def test_preemption_victim_prices_with_p90_when_available():
    s = make_scheduler(num_blocks=7, block_size=4)
    g1, _ = add_request(s, "0", 8, predicted_len=10)
    g2, _ = add_request(s, "1", 8, predicted_len=500)
    g3, _ = add_request(s, "2", 8, predicted_len=10)
    g3.predicted_len_p90 = 800  # calibrated tail dwarfs g2's p50
    s.schedule()
    for g in (g1, g2, g3):
        append_token(g)
    metas, _ = s.schedule()
    assert [m.request_id for m in metas] == ["0", "1"]
    assert g3.get_seqs()[0].status == SequenceStatus.WAITING
