"""BlockSpaceManager unit tests (reference test strategy: engine-logic
tests with synthetic sequences, SURVEY §4)."""
import pytest

from intellillm_tpu.block import PhysicalTokenBlock
from intellillm_tpu.core.block_manager import (AllocStatus, BlockAllocator,
                                               BlockSpaceManager)
from intellillm_tpu.sampling_params import SamplingParams
from intellillm_tpu.sequence import Sequence, SequenceGroup, SequenceStatus
from intellillm_tpu.utils import Device


def make_group(seq_id, prompt_len, block_size=4, best_of=1):
    seq = Sequence(seq_id, "x", list(range(prompt_len)), block_size)
    sp = SamplingParams(temperature=1.0 if best_of > 1 else 0.0,
                       best_of=best_of, n=best_of)
    return SequenceGroup(f"req{seq_id}", [seq], sp, 0.0), seq


def test_block_allocator_refcounting():
    alloc = BlockAllocator(Device.DEVICE, 4, 4)
    blocks = [alloc.allocate() for _ in range(4)]
    assert alloc.get_num_free_blocks() == 0
    with pytest.raises(ValueError):
        alloc.allocate()
    for b in blocks:
        alloc.free(b)
        with pytest.raises(ValueError):
            alloc.free(b)  # double free
    assert alloc.get_num_free_blocks() == 4


def test_allocate_and_free():
    bm = BlockSpaceManager(block_size=4, num_device_blocks=8,
                           num_cpu_blocks=4)
    group, seq = make_group(0, prompt_len=10)  # 3 blocks
    assert bm.can_allocate(group) == AllocStatus.OK
    bm.allocate(group)
    assert len(bm.get_block_table(seq)) == 3
    assert bm.get_num_free_device_blocks() == 5
    bm.free(seq)
    assert bm.get_num_free_device_blocks() == 8


def test_allocate_never_fits():
    bm = BlockSpaceManager(block_size=4, num_device_blocks=2,
                           num_cpu_blocks=2)
    group, _ = make_group(0, prompt_len=100)
    assert bm.can_allocate(group) == AllocStatus.NEVER


def test_append_slots_grows_and_cow():
    bm = BlockSpaceManager(block_size=4, num_device_blocks=8,
                           num_cpu_blocks=4)
    group, seq = make_group(0, prompt_len=4, best_of=2)
    seq.status = SequenceStatus.WAITING
    bm.allocate(group)
    seq.status = SequenceStatus.RUNNING

    # Fork: child shares blocks.
    child = seq.fork(1)
    group.add(child)
    bm.fork(seq, child)
    table = bm.block_tables[seq.seq_id]
    assert all(b.ref_count == 2 for b in table)

    # Append a token to parent: prompt block full → new block, no CoW.
    seq.append_token_id(7, {7: 0.0})
    cows = bm.append_slots(seq, 1)
    assert cows == []
    assert len(bm.block_tables[seq.seq_id]) == 2

    # Parent's new last block is unshared; append within it → no CoW.
    seq.append_token_id(8, {8: 0.0})
    assert bm.append_slots(seq, 1) == []

    # Child appends: its last block (the shared prompt block) is full, so
    # a new block is allocated; no CoW needed for full blocks.
    child.append_token_id(9, {9: 0.0})
    assert bm.append_slots(child, 1) == []


def test_cow_on_shared_partial_block():
    bm = BlockSpaceManager(block_size=4, num_device_blocks=8,
                           num_cpu_blocks=4)
    # Prompt 2 tokens → one partially-filled block, then fork.
    group, seq = make_group(0, prompt_len=2, best_of=2)
    bm.allocate(group)
    seq.status = SequenceStatus.RUNNING
    child = seq.fork(1)
    group.add(child)
    bm.fork(seq, child)

    seq.append_token_id(5, {5: 0.0})
    cows = bm.append_slots(seq, 1)
    assert len(cows) == 1  # shared partial block copied
    src, dst = cows[0]
    assert src != dst
    # Parent's table now unshared.
    assert bm.block_tables[seq.seq_id][-1].ref_count == 1


def test_multi_slot_reservation():
    bm = BlockSpaceManager(block_size=4, num_device_blocks=8,
                           num_cpu_blocks=4)
    group, seq = make_group(0, prompt_len=4)
    bm.allocate(group)
    seq.status = SequenceStatus.RUNNING
    seq.append_token_id(1, {1: 0.0})
    # Reserve 8 lookahead slots: tokens at positions 4..11 → 3 blocks total.
    bm.append_slots(seq, 8)
    assert len(bm.block_tables[seq.seq_id]) == 3


def test_swap_out_and_in():
    bm = BlockSpaceManager(block_size=4, num_device_blocks=4,
                           num_cpu_blocks=4)
    group, seq = make_group(0, prompt_len=8, best_of=2)
    bm.allocate(group)
    for s in group.get_seqs():
        s.status = SequenceStatus.RUNNING

    assert bm.can_swap_out(group)
    mapping = bm.swap_out(group)
    assert len(mapping) == 2
    assert bm.get_num_free_device_blocks() == 4
    for s in group.get_seqs():
        s.status = SequenceStatus.SWAPPED

    assert bm.can_swap_in(group)
    mapping_in = bm.swap_in(group)
    assert set(mapping_in.keys()) == set(mapping.values())
    assert bm.get_num_free_device_blocks() == 2


def test_can_swap_in_budgets_multi_step_slots():
    """ADVICE r1: swap-in must budget the K lookahead slots the scheduler
    reserves right after (CoW block + blocks covering K tokens per seq),
    not just +1 block — otherwise allocate() can raise mid-step."""
    bm = BlockSpaceManager(block_size=4, num_device_blocks=6,
                           num_cpu_blocks=8, watermark=0.0)
    group, seq = make_group(0, prompt_len=8, best_of=2)
    bm.allocate(group)
    for s in group.get_seqs():
        s.status = SequenceStatus.RUNNING
    # Fork a real second sequence so the group swaps TWO sequences (the
    # per-seq multiplier in can_swap_in must be exercised with
    # num_swapped > 1).
    child = seq.fork(1)
    group.add(child)
    child.status = SequenceStatus.RUNNING
    bm.fork(seq, child)
    bm.swap_out(group)
    for s in group.get_seqs():
        s.status = SequenceStatus.SWAPPED
    assert group.num_seqs(status=SequenceStatus.SWAPPED) == 2

    # 6 free device blocks; the shared table needs 2 blocks, K=1 needs
    # 2 headroom blocks per seq -> 2 + 2*2 = 6 fits exactly.
    assert bm.can_swap_in(group, num_slots=1)
    # K=8 lookahead needs 1 CoW + ceil((8-1)/4)+1 = 3 blocks per seq ->
    # 2 + 2*3 = 8 > 6 free: must defer.
    assert not bm.can_swap_in(group, num_slots=8)
