"""bench.py backend-probe hardening (BENCH_r04/r05 dark trajectory).

A hung probe must (a) be killed — process GROUP and all — within its
budget, (b) leave a structured probe record with the faulthandler stack,
and (c) let the bench emit a parseable `skipped` record instead of
hanging the whole run.
"""
import json
import time

import pytest

import bench


@pytest.fixture(autouse=True)
def _reset_progress():
    bench._PROGRESS["phase"] = "start"
    bench._PROGRESS["probe"] = []
    bench._PROGRESS["warmup_tok_s"] = None
    yield


@pytest.fixture
def fast_probe_env(monkeypatch):
    monkeypatch.setenv("INTELLILLM_BENCH_PROBE_ATTEMPTS", "1")
    monkeypatch.setenv("INTELLILLM_BENCH_PROBE_BACKOFF", "0")
    monkeypatch.setenv("INTELLILLM_BENCH_PROBE_TIMEOUT", "3")


def test_hung_probe_is_killed_within_budget(monkeypatch, fast_probe_env):
    monkeypatch.setattr(bench, "_probe_child_code",
                        lambda timeout_s: "import time; time.sleep(600)")
    t0 = time.monotonic()
    assert bench.probe_backend() is False
    assert time.monotonic() - t0 < 30
    [rec] = bench._PROGRESS["probe"]
    assert rec["ok"] is False
    assert "hung" in rec["err"]


def test_hung_probe_with_grandchild_holding_pipe(monkeypatch,
                                                 fast_probe_env):
    """A child that forks a helper (TPU runtimes do) and hangs: the
    helper inherits the stderr pipe, so a direct-child-only kill leaves
    `communicate()` blocked forever. The process-group kill must reap
    both within budget."""
    child = ("import subprocess, sys, time\n"
             "subprocess.Popen(['sleep', '600'], stderr=sys.stderr)\n"
             "time.sleep(600)\n")
    monkeypatch.setattr(bench, "_probe_child_code", lambda t: child)
    t0 = time.monotonic()
    assert bench.probe_backend() is False
    assert time.monotonic() - t0 < 30
    [rec] = bench._PROGRESS["probe"]
    assert "hung" in rec["err"]


def test_wedged_probe_captures_faulthandler_stack(monkeypatch,
                                                  fast_probe_env):
    """A child that self-dumps via faulthandler (the real probe's wedge
    path) must yield a probe record carrying the stack."""
    child = ("import faulthandler, time\n"
             "faulthandler.dump_traceback_later(0.5, exit=True)\n"
             "time.sleep(600)\n")
    monkeypatch.setattr(bench, "_probe_child_code", lambda t: child)
    assert bench.probe_backend() is False
    [rec] = bench._PROGRESS["probe"]
    assert "stack" in rec
    assert "Timeout (" in rec["stack"]


def test_probe_succeeds_on_cpu(monkeypatch):
    """The real probe child against the CPU backend: exits 0, reports
    the platform, one ok record."""
    monkeypatch.setenv("INTELLILLM_BENCH_PROBE_ATTEMPTS", "1")
    monkeypatch.setenv("INTELLILLM_BENCH_PROBE_BACKOFF", "0")
    monkeypatch.setenv("INTELLILLM_BENCH_PROBE_TIMEOUT", "120")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    assert bench.probe_backend() is True
    [rec] = bench._PROGRESS["probe"]
    assert rec["ok"] is True
    assert rec["platform"] == "cpu"


def test_probe_budget_is_clamped(monkeypatch, capsys):
    """Env overrides beyond the fail-fast budget are clamped IN the
    loop (BENCH_r05 carried 3x300s through the env)."""
    monkeypatch.setenv("INTELLILLM_BENCH_PROBE_ATTEMPTS", "5")
    monkeypatch.setenv("INTELLILLM_BENCH_PROBE_BACKOFF", "0")
    monkeypatch.setenv("INTELLILLM_BENCH_PROBE_TIMEOUT", "900")
    monkeypatch.setattr(bench, "_probe_child_code",
                        lambda t: "raise SystemExit(1)")
    assert bench.probe_backend() is False
    assert len(bench._PROGRESS["probe"]) == bench._MAX_PROBE_ATTEMPTS
    assert "clamping probe budget" in capsys.readouterr().err


def test_extract_probe_stack():
    dump = "noise\nTimeout (0:00:50)!\nThread 0x1 (most recent call)\n"
    assert bench._extract_probe_stack(dump).startswith("Timeout (")
    assert bench._extract_probe_stack(dump.encode()).startswith("Timeout (")
    assert bench._extract_probe_stack("no marker here") is None
    assert bench._extract_probe_stack(None) is None


def test_skip_record_is_structured(capsys):
    bench._PROGRESS["phase"] = "probe"
    bench._PROGRESS["probe"] = [{"attempt": 1, "ok": False,
                                 "err": "probe hung > 3s (killed)"}]
    bench._skip_record("TPU backend unavailable after all probe retries")
    rec = json.loads(capsys.readouterr().out.strip())
    assert rec["metric"] == "skipped"
    assert rec["value"] == 0
    assert rec["phase"] == "probe"
    assert rec["probe_attempts"][0]["err"].startswith("probe hung")
