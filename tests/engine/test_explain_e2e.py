"""Forced-contention e2e for scheduler decision tracing (PR 17
acceptance): a memory-pressured, budget-starved, fairness-capped engine
run on CPU must leave behind (a) per-request `/debug/explain/{id}`
decompositions whose cause-seconds sum to the SLO-measured queue-wait
within tolerance, and (b) `intellillm_sched_deferred_seconds_total`
nonzero for exactly the induced causes — on BOTH API servers.
"""
import asyncio

import pytest
from aiohttp.test_utils import TestClient, TestServer

from intellillm_tpu import LLM, SamplingParams, tenancy
from intellillm_tpu.lora.request import LoRARequest
from intellillm_tpu.obs import decisions as decisions_mod
from intellillm_tpu.obs import get_flight_recorder
from intellillm_tpu.tenancy import TenantSpec, get_tenant_registry

from tests.lora.test_lora import make_adapter

# Causes this scenario can legitimately induce. `unattributed` is never
# exported; `lora_cap` can't bind (1 adapter, max_loras=2); nothing
# else exists in the vocabulary.
_INDUCIBLE = {"token_budget", "tenant_fairness", "kv_watermark",
              "max_seqs", "padding", "preempted", "swap_backlog"}

# ~36-42 word-level tokens each: with a 48-token step budget only one
# prefill fits per pass, so every pass leaves someone blocked on
# token_budget.
_PROMPTS = [
    " ".join(["the cat runs fast and the dog"] * 6),
    " ".join(["the president of the united states is"] * 6),
    " ".join(["the capital of france is paris"] * 6),
    " ".join(["hello my name is"] * 9),
]


def _run(app, scenario):
    async def go():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            await scenario(client)
        finally:
            await client.close()
    asyncio.run(go())


def _scrape_deferred_seconds(metrics_text):
    out = {}
    for line in metrics_text.splitlines():
        if not line.startswith("intellillm_sched_deferred_seconds_total{"):
            continue
        labels, value = line.rsplit(None, 1)
        cause = labels.split('cause="', 1)[1].split('"', 1)[0]
        out[cause] = float(value)
    return out


def test_forced_contention_explains_queue_wait(tiny_llama_dir, tmp_path,
                                               monkeypatch):
    decisions_mod.reset_for_testing()
    get_flight_recorder().reset_for_testing()
    tenancy.reset_for_testing()

    adapter = make_adapter(str(tmp_path / "hog-ad"), seed=31, rank=4,
                           alpha=8.0, targets=("q_proj", "v_proj"))
    hog_req = LoRARequest("hog", 1, adapter)
    # Hog capped at a quarter of the step budget; victims ride the
    # default tenant, so the fairness pass sees 2 present tenants.
    get_tenant_registry().register(
        TenantSpec("hog", lora_request=hog_req, weight=1.0,
                   token_share_cap=0.25))

    # Count real preemptions to prove the pool forced at least one.
    from intellillm_tpu.core import scheduler as sched_mod
    preemptions = {"n": 0}
    orig_preempt = sched_mod.Scheduler._preempt

    def counting(self, *a, **kw):
        preemptions["n"] += 1
        return orig_preempt(self, *a, **kw)

    monkeypatch.setattr(sched_mod.Scheduler, "_preempt", counting)

    llm = LLM(model=tiny_llama_dir, dtype="float32",
              num_device_blocks_override=10, max_model_len=128,
              max_num_seqs=8, max_paddings=512, swap_space=0.01,
              max_num_batched_tokens=48, enable_lora=True, max_loras=2,
              max_lora_rank=8)
    engine = llm.llm_engine
    params = SamplingParams(temperature=0.0, max_tokens=24,
                            ignore_eos=True)
    rids, hog_rids = [], []
    for i, prompt in enumerate(_PROMPTS):
        for req in (None, hog_req):
            rid = str(len(rids))  # _run_engine sorts ids numerically
            engine.add_request(rid, prompt, params, lora_request=req)
            rids.append(rid)
            if req is not None:
                hog_rids.append(rid)
    outs = {o.request_id: o for o in llm._run_engine(use_tqdm=False)}
    assert set(outs) == set(rids)
    assert preemptions["n"] >= 1, (
        "pool was sized to force preemption but none happened")

    dlog = decisions_mod.get_decision_log()
    summary = dlog.summary()
    deferred = summary["deferred_seconds_by_cause"]
    assert deferred, "no contention recorded by the decision log"

    try:
        from intellillm_tpu.entrypoints import api_server as demo_server
        from intellillm_tpu.entrypoints.openai import (
            api_server as openai_server)

        async def scenario(client):
            # (b) fleet counters: nonzero for exactly induced causes.
            resp = await client.get("/metrics")
            assert resp.status == 200
            exported = _scrape_deferred_seconds(await resp.text())
            nonzero = {c for c, s in exported.items() if s > 0}
            # Guaranteed by construction: a 48-token budget vs 36-42
            # token prompts starves prefills; the hog's 0.25 share cap
            # defers it while victims wait; the 10-block pool preempts.
            assert {"token_budget", "tenant_fairness",
                    "preempted"} <= nonzero, sorted(nonzero)
            assert nonzero <= _INDUCIBLE, sorted(nonzero - _INDUCIBLE)
            assert "unattributed" not in exported

            # The same ledger rides /health/detail for top/serve_bench.
            resp = await client.get("/health/detail")
            contention = (await resp.json())["contention"]
            assert contention["decisions"]["requeue"] >= 1
            for cause in ("token_budget", "tenant_fairness", "preempted"):
                assert contention["deferred_seconds_by_cause"][cause] > 0

            # (a) per-request explains: by_cause sums to the SLO-
            # measured queue-wait within tolerance, for every request.
            for rid in rids:
                resp = await client.get(f"/debug/explain/{rid}")
                assert resp.status == 200, rid
                data = await resp.json()
                assert data["found"] is True, rid
                assert data["state"] == "finished", rid
                qw = data["queue_wait"]
                attributed = qw["total_s"]
                assert attributed == pytest.approx(
                    sum(qw["by_cause"].values()), abs=1e-5), rid
                measured = qw["measured_s"]
                # Attribution (monotonic clock, charged at verdict
                # sites) vs measurement (wall clock, recorder events
                # at the same logical points): small skew only.
                assert abs(measured - attributed) <= max(
                    0.1, 0.25 * measured), (
                    f"{rid}: measured={measured:.4f}s "
                    f"attributed={attributed:.4f}s by={qw['by_cause']}")
                assert qw["unexplained_s"] <= max(0.1, 0.25 * measured)

            # The hog specifically paid fairness time; at least one
            # request stalled post-preemption.
            resp = await client.get(f"/debug/explain/{hog_rids[-1]}")
            hog = await resp.json()
            assert "tenant_fairness" in hog["queue_wait"]["by_cause"]
            stalls = 0
            for rid in rids:
                resp = await client.get(f"/debug/explain/{rid}")
                data = await resp.json()
                stalls += data["stall"]["total_s"] > 0
            assert stalls >= 1, "a preempted request must show stall time"

        _run(demo_server.build_app(), scenario)
        _run(openai_server.build_app(), scenario)
    finally:
        get_flight_recorder().reset_for_testing()
        tenancy.reset_for_testing()
        decisions_mod.reset_for_testing()
