"""Pipelined stepping (LLMEngine.step_pipelined) equivalence.

The pipelined driver dispatches decode step N+1 before fetching step N
(continuation programs slice their input tokens from the previous step's
on-device output) and chains prompt admissions behind in-flight steps.
Outputs must match the serial step() loop token-for-token: greedy results
are schedule-independent, and sampling seeds are keyed per
(sequence, output-position) so pipelining cannot change random streams
either.
"""
import pytest

from intellillm_tpu import LLM, SamplingParams


def _build(model_dir, **kw):
    args = dict(dtype="float32", num_device_blocks_override=128,
                max_model_len=128, max_num_seqs=8, max_paddings=512,
                swap_space=0.01, num_decode_steps=8)
    args.update(kw)
    return LLM(model=model_dir, **args)


def _collect(outs):
    done = {}
    for o in outs:
        if o.finished:
            done[o.request_id] = [
                (tuple(c.token_ids), c.text, c.finish_reason)
                for c in o.outputs]
    return done


def _run_serial(llm, requests):
    engine = llm.llm_engine
    for rid, prompt, params in requests:
        engine.add_request(rid, prompt, params)
    outs = []
    while engine.has_unfinished_requests():
        outs.extend(engine.step())
    return _collect(outs)


def _run_pipelined(llm, requests, stagger_after=None):
    """Drive step_pipelined; with stagger_after=n, add the remaining
    requests only after n pipelined calls (exercises prompt admission
    chained behind in-flight decode steps)."""
    engine = llm.llm_engine
    first = requests if stagger_after is None else requests[:stagger_after]
    rest = [] if stagger_after is None else requests[stagger_after:]
    for rid, prompt, params in first:
        engine.add_request(rid, prompt, params)
    outs = []
    calls = 0
    while engine.has_unfinished_requests() or engine.has_inflight():
        outs.extend(engine.step_pipelined())
        calls += 1
        if rest and calls >= 2:
            for rid, prompt, params in rest:
                engine.add_request(rid, prompt, params)
            rest = []
        assert calls < 2000, "pipelined engine made no progress"
    return _collect(outs)


def test_pipelined_matches_serial_greedy(tiny_llama_dir, example_prompts):
    reqs = [(str(i), p, SamplingParams(temperature=0.0, max_tokens=24,
                                       ignore_eos=True))
            for i, p in enumerate(example_prompts)]
    ref = _run_serial(_build(tiny_llama_dir), reqs)
    got = _run_pipelined(_build(tiny_llama_dir), reqs)
    assert got == ref
    # The pipelined run really exercised continuations (not just drains):
    # max_tokens=24 at K=8 needs >= 2 extra fused steps per sequence.
    assert all(r[0][2] == "length" for r in got.values())


def test_pipelined_staggered_admission(tiny_llama_dir, example_prompts):
    """Requests added mid-decode are admitted via prefill chaining; the
    final outputs still match the serial loop."""
    reqs = [(str(i), p, SamplingParams(temperature=0.0, max_tokens=20,
                                       ignore_eos=True))
            for i, p in enumerate(example_prompts)]
    ref = _run_serial(_build(tiny_llama_dir), reqs)
    got = _run_pipelined(_build(tiny_llama_dir), reqs, stagger_after=2)
    assert got == ref


def test_pipelined_stops_make_zombie_rows(tiny_opt_dir, example_prompts):
    """A sequence hitting a stop mid-pipeline becomes a zombie row (its
    in-flight overshoot is discarded, its KV pages deferred-freed); the
    surviving sequences finish with serial-identical outputs."""
    probe = _run_serial(
        _build(tiny_opt_dir),
        [("p", example_prompts[0],
          SamplingParams(temperature=0.0, max_tokens=4))])
    first_word = probe["p"][0][1].strip().split()[0]
    params = [
        SamplingParams(temperature=0.0, max_tokens=32, stop=[first_word]),
        SamplingParams(temperature=0.0, max_tokens=32, ignore_eos=True),
        SamplingParams(temperature=0.0, max_tokens=32, ignore_eos=True),
    ]
    reqs = [(str(i), p, sp)
            for i, (p, sp) in enumerate(zip(example_prompts, params))]
    ref = _run_serial(_build(tiny_opt_dir), reqs)
    got = _run_pipelined(_build(tiny_opt_dir), reqs)
    assert got == ref
    assert ref["0"][0][2] == "stop"          # the zombie actually stopped


def test_pipelined_random_sampling_matches(tiny_llama_dir, example_prompts):
    """Seeded random sampling: continuation seeds advance exactly as a
    caught-up fresh dispatch would compute them."""
    reqs = [(str(i), p, SamplingParams(temperature=0.8, top_p=0.9,
                                       max_tokens=16, ignore_eos=True))
            for i, p in enumerate(example_prompts)]
    ref = _run_serial(_build(tiny_llama_dir), reqs)
    got = _run_pipelined(_build(tiny_llama_dir), reqs)
    assert got == ref


def test_pipelined_best_of_groups(tiny_llama_dir, example_prompts):
    """Multi-sequence groups (best_of>1 random): forked rows continue
    correctly (the post-prefill fresh decode resolves CoW; continuations
    only ever extend private trailing blocks)."""
    reqs = [(str(i), p, SamplingParams(temperature=0.7, best_of=2, n=2,
                                       max_tokens=12, ignore_eos=True))
            for i, p in enumerate(example_prompts[:3])]
    ref = _run_serial(_build(tiny_llama_dir), reqs)
    got = _run_pipelined(_build(tiny_llama_dir), reqs)
    assert got == ref


def test_pipelined_tight_pool_drains(tiny_llama_dir):
    """When in-place growth runs out of free blocks the pipeline drains to
    a full scheduling pass (which may preempt) instead of corrupting the
    pool; the request still completes."""
    llm = _build(tiny_llama_dir, num_device_blocks_override=12,
                 max_num_seqs=2, num_decode_steps=8)
    reqs = [("0", None, SamplingParams(temperature=0.0, max_tokens=16,
                                       ignore_eos=True))]
    engine = llm.llm_engine
    engine.add_request("0", None, reqs[0][2],
                       prompt_token_ids=[2, 3, 4, 5] * 20)  # 80 tokens
    outs = []
    calls = 0
    while engine.has_unfinished_requests() or engine.has_inflight():
        outs.extend(engine.step_pipelined())
        calls += 1
        assert calls < 200
    done = _collect(outs)
    assert len(done["0"][0][0]) >= 16


def test_pipelined_continuous_arrivals(tiny_llama_dir, example_prompts):
    """High-rate pattern: a new request arrives on (almost) every call,
    so prompt admissions interleave with decode continuations chained
    PAST them (the _cont_budget_ok path). Outputs must still match the
    serial loop exactly."""
    prompts = (example_prompts * 3)[:10]
    reqs = [(str(i), p, SamplingParams(temperature=0.0, max_tokens=16,
                                       ignore_eos=True))
            for i, p in enumerate(prompts)]
    ref = _run_serial(_build(tiny_llama_dir, max_num_seqs=12), reqs)

    llm = _build(tiny_llama_dir, max_num_seqs=12)
    engine = llm.llm_engine
    outs = []
    pending = list(reqs)
    calls = 0
    engine.add_request(*pending.pop(0))
    while (engine.has_unfinished_requests() or engine.has_inflight()
           or pending):
        if pending:
            engine.add_request(*pending.pop(0))
        outs.extend(engine.step_pipelined())
        calls += 1
        assert calls < 2000
    assert _collect(outs) == ref


def test_no_overshoot_cont_when_budgets_exhausted(tiny_llama_dir,
                                                  example_prompts,
                                                  monkeypatch):
    """max_tokens == K (the offline-bench shape): after the one fused
    decode call covers every row's budget, the pipeline must NOT dispatch
    a continuation — it would be a 100% overshoot device call."""
    llm = _build(tiny_llama_dir, num_decode_steps=8)
    engine = llm.llm_engine
    calls = {"cont": 0}
    orig = engine.worker.execute_decode_cont

    def counting(*a, **kw):
        calls["cont"] += 1
        return orig(*a, **kw)

    monkeypatch.setattr(engine.worker, "execute_decode_cont", counting)
    for i, p in enumerate(example_prompts):
        engine.add_request(str(i), p,
                           SamplingParams(temperature=0.0, max_tokens=8,
                                          ignore_eos=True))
    outs = []
    n = 0
    while engine.has_unfinished_requests() or engine.has_inflight():
        outs.extend(engine.step_pipelined())
        n += 1
        assert n < 100
    done = _collect(outs)
    assert all(len(v[0][0]) == 8 for v in done.values())
    assert calls["cont"] == 0, (
        "pipeline dispatched a pure-overshoot continuation")


def test_pipelined_k1_falls_back(tiny_opt_dir, example_prompts):
    """K=1 batches (no continuation program) still work through the
    pipelined driver — each step drains before the next fresh schedule."""
    reqs = [(str(i), p, SamplingParams(temperature=0.0, max_tokens=8,
                                       ignore_eos=True))
            for i, p in enumerate(example_prompts[:2])]
    ref = _run_serial(_build(tiny_opt_dir, num_decode_steps=1), reqs)
    got = _run_pipelined(_build(tiny_opt_dir, num_decode_steps=1), reqs)
    assert got == ref
