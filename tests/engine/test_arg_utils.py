"""CLI argument plumbing (engine/arg_utils.py): the deprecated no-op
--enable-chunked-prefill warns and still resolves to chunking ON, the
--replica-role flag round-trips into SchedulerConfig, and the silent
default path stays silent."""
import argparse
import warnings

import pytest

from intellillm_tpu.engine.arg_utils import EngineArgs


def _parse(argv):
    parser = EngineArgs.add_cli_args(argparse.ArgumentParser())
    return parser.parse_args(argv)


def test_enable_chunked_prefill_flag_warns_and_stays_on():
    args = _parse(["--model", "m", "--enable-chunked-prefill"])
    with pytest.warns(DeprecationWarning, match="no-op"):
        engine_args = EngineArgs.from_cli_args(args)
    # The sentinel never leaks: the flag resolves back to the default.
    assert engine_args.enable_chunked_prefill is True
    assert engine_args.disable_chunked_prefill is False


def test_no_warning_without_the_flag():
    args = _parse(["--model", "m"])
    with warnings.catch_warnings():
        warnings.simplefilter("error", DeprecationWarning)
        engine_args = EngineArgs.from_cli_args(args)
    assert engine_args.enable_chunked_prefill is True


def test_disable_chunked_prefill_still_works():
    args = _parse(["--model", "m", "--disable-chunked-prefill"])
    with warnings.catch_warnings():
        warnings.simplefilter("error", DeprecationWarning)
        engine_args = EngineArgs.from_cli_args(args)
    assert engine_args.disable_chunked_prefill is True


@pytest.mark.parametrize("role", ["mixed", "prefill", "decode"])
def test_replica_role_round_trips(role):
    args = _parse(["--model", "m", "--replica-role", role])
    engine_args = EngineArgs.from_cli_args(args)
    assert engine_args.replica_role == role


def test_replica_role_rejects_unknown():
    with pytest.raises(SystemExit):
        _parse(["--model", "m", "--replica-role", "draft"])
    from intellillm_tpu.config import SchedulerConfig
    with pytest.raises(ValueError, match="replica_role"):
        SchedulerConfig(max_num_batched_tokens=512, max_num_seqs=4,
                        max_model_len=128, max_paddings=512,
                        replica_role="draft")
