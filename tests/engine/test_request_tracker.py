"""RequestTracker unit tests (reference
`tests/async_engine/test_request_tracker.py`)."""
import asyncio

import pytest

from intellillm_tpu.engine.async_llm_engine import (AsyncEngineDeadError,
                                                    AsyncStream,
                                                    RequestTracker)
from intellillm_tpu.outputs import RequestOutput


def _run(coro):
    return asyncio.run(coro)


def _output(request_id, finished=False):
    return RequestOutput(request_id=request_id, prompt="p",
                         prompt_token_ids=[1], prompt_logprobs=None,
                         outputs=[], finished=finished)


def test_add_and_collect_requests():
    async def run():
        tracker = RequestTracker()
        tracker.init_event()
        stream = tracker.add_request("1", prompt="x")
        assert tracker.new_requests_event.is_set()
        new, finished = tracker.get_new_and_finished_requests()
        assert len(new) == 1 and new[0]["request_id"] == "1"
        assert not finished
        assert "1" in tracker
        assert not tracker.new_requests_event.is_set()
        with pytest.raises(KeyError):
            tracker.add_request("1", prompt="dup")
    _run(run())


def test_abort_before_scheduling_drops_request():
    async def run():
        tracker = RequestTracker()
        tracker.init_event()
        tracker.add_request("1", prompt="x")
        tracker.abort_request("1")
        new, finished = tracker.get_new_and_finished_requests()
        assert new == []
        assert finished == {"1"}
        assert "1" not in tracker
    _run(run())


def test_finished_output_finishes_stream():
    async def run():
        tracker = RequestTracker()
        tracker.init_event()
        stream = tracker.add_request("1", prompt="x")
        tracker.get_new_and_finished_requests()
        tracker.process_request_output(_output("1", finished=True))
        assert stream.finished
        got = [out async for out in stream]
        assert len(got) == 1 and got[0].finished
    _run(run())


def test_propagate_exception_reaches_streams():
    async def run():
        tracker = RequestTracker()
        tracker.init_event()
        stream = tracker.add_request("1", prompt="x")
        tracker.get_new_and_finished_requests()
        tracker.propagate_exception(AsyncEngineDeadError("boom"))
        with pytest.raises(AsyncEngineDeadError):
            async for _ in stream:
                pass
    _run(run())


def test_output_for_aborted_request_is_dropped():
    async def run():
        tracker = RequestTracker()
        tracker.init_event()
        tracker.add_request("1", prompt="x")
        tracker.get_new_and_finished_requests()
        tracker.abort_request("1")
        # Late output from the engine loop must be ignored, not crash.
        tracker.process_request_output(_output("1"))
    _run(run())
