"""Golden invariance across the kernel-selection flags.

The `INTELLILLM_PALLAS_*` flags choose a path at trace time inside the
same jit programs — flipping them must not change greedy outputs
anywhere the reference runs (on CPU both settings resolve to the same
reference composition, so outputs are bit-identical BY CONSTRUCTION and
this pins the construction), and must not change the executable count
or bucketing (the zero-new-executables acceptance criterion, checked
via CompileTracker deltas). On TPU the same tests compare the Pallas
kernels against the reference for real.

The workload is deliberately a MIXED batch: several prompts admitted
together with a small token budget, so steps interleave decode rows
with prefill-chunk rows — the exact shape the ragged fused kernel
serves.
"""
import pytest

from intellillm_tpu import LLM, SamplingParams
from intellillm_tpu.obs import get_compile_tracker


def _build(model_dir, **kw):
    args = dict(dtype="float32", num_device_blocks_override=128,
                max_model_len=128, max_num_seqs=4, max_paddings=512,
                swap_space=0.01, num_decode_steps=1,
                max_num_batched_tokens=16)
    args.update(kw)
    return LLM(model=model_dir, **args)


def _greedy(llm, prompts, max_tokens=8):
    params = SamplingParams(temperature=0.0, max_tokens=max_tokens)
    outs = llm.generate(prompts, params)
    return [tuple(o.outputs[0].token_ids) for o in outs]


def _run_flagged(model_dir, prompts, monkeypatch, ragged, bgmv):
    monkeypatch.setenv("INTELLILLM_PALLAS_RAGGED", ragged)
    monkeypatch.setenv("INTELLILLM_PALLAS_BGMV", bgmv)
    before = get_compile_tracker().snapshot()
    llm = _build(model_dir)
    tokens = _greedy(llm, prompts)
    after = get_compile_tracker().snapshot()
    compiles = {p: after["compiles"].get(p, 0)
                - before["compiles"].get(p, 0)
                for p in set(before["compiles"]) | set(after["compiles"])}
    # Dispatches of the mixed program during THIS run (fresh compiles +
    # warm cache hits): proves the workload actually drove the mixed
    # hot path regardless of what earlier tests in the process warmed.
    mixed = sum(after[k].get("mixed", 0) - before[k].get("mixed", 0)
                for k in ("compiles", "cache_hits"))
    del llm
    return tokens, {p: n for p, n in compiles.items() if n}, mixed


def test_mixed_greedy_identical_across_kernel_flags(tiny_llama_dir,
                                                    example_prompts,
                                                    monkeypatch):
    """Flag flip: identical greedy tokens AND identical per-program
    compile deltas on the same mixed workload (chunked prefill + decode
    rows interleaved under a 16-token budget)."""
    prompts = example_prompts[:4]
    tok_off, _, mixed_off = _run_flagged(
        tiny_llama_dir, prompts, monkeypatch, "0", "0")
    tok_on, compiles_on, mixed_on = _run_flagged(
        tiny_llama_dir, prompts, monkeypatch, "1", "1")
    assert tok_on == tok_off
    # Both runs must actually exercise the mixed hot path. (Earlier
    # tests in the same process may have warmed the identical buckets —
    # CompileTracker keys are process-global — so compile deltas alone
    # can't prove the workload ran; dispatch counts can.)
    assert mixed_off > 0 and mixed_on > 0
    # The flags-on run must land in (program, bucket) keys the process
    # has already compiled — the flags-off run just dispatched the very
    # same workload — so its compile delta is empty. Any key here means
    # the kernel-selection flags leaked into jit bucketing.
    assert compiles_on == {}, (
        "kernel-selection flags created new jit buckets: "
        f"{compiles_on}")


def test_lora_mixed_batch_identical_across_bgmv_flag(tmp_path_factory,
                                                     example_prompts,
                                                     monkeypatch):
    """Adapter rows and no-adapter rows in the same batch, BGMV flag off
    vs on: identical outputs (slot-0 rows ride the exact +0.0 guarantee
    on either path)."""
    pytest.importorskip("safetensors")
    from intellillm_tpu.lora.request import LoRARequest
    from tests.lora.test_lora import make_adapter
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM
    from tests.conftest import _build_word_tokenizer

    root = tmp_path_factory.mktemp("kernel-golden-lora")
    base = str(root / "base")
    _, vocab_size = _build_word_tokenizer(base)
    torch.manual_seed(0)
    config = LlamaConfig(
        vocab_size=vocab_size, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rms_norm_eps=1e-6, pad_token_id=0,
        eos_token_id=1, bos_token_id=1, tie_word_embeddings=False,
        torch_dtype=torch.float32)
    LlamaForCausalLM(config).eval().save_pretrained(
        base, safe_serialization=True)
    adapter = make_adapter(str(root / "ad"), seed=11, rank=4, alpha=8.0)

    def run(flag):
        monkeypatch.setenv("INTELLILLM_PALLAS_BGMV", flag)
        llm = _build(base, enable_lora=True, max_loras=2, max_lora_rank=8,
                     max_model_len=64)
        params = SamplingParams(temperature=0.0, max_tokens=6)
        reqs = [LoRARequest("ad", 1, adapter), None,
                LoRARequest("ad", 1, adapter)]
        engine = llm.llm_engine
        for i, (prompt, req) in enumerate(zip(example_prompts[:3], reqs)):
            engine.add_request(str(i), prompt, params, lora_request=req)
        outs = {o.request_id: o for o in llm._run_engine(use_tqdm=False)}
        toks = [tuple(outs[str(i)].outputs[0].token_ids)
                for i in range(3)]
        del llm
        return toks

    assert run("0") == run("1")
