"""AsyncLLMEngine background-loop unit tests with a mock engine.

Role parity: reference `tests/async_engine/test_async_llm_engine.py` —
the loop must step while work exists, go idle (await the new-request
event) when drained, and wake on the next add_request; plus the
pipelined variant's has_inflight continuation condition.
"""
import asyncio

import pytest

from intellillm_tpu.engine.async_llm_engine import AsyncLLMEngine
from intellillm_tpu.outputs import CompletionOutput, RequestOutput


class _MockEngine:
    def __init__(self, pipeline=False):
        self.pipeline_enabled = pipeline
        self.step_calls = 0
        self.requests = []
        self._inflight = 0

    # --- engine surface the async wrapper uses ---
    def add_request(self, request_id, **kwargs):
        self.requests.append(request_id)

    def abort_request(self, request_ids):
        for rid in request_ids:
            if rid in self.requests:
                self.requests.remove(rid)

    def has_inflight(self):
        return self._inflight > 0

    def _emit(self, rid, finished):
        return RequestOutput(
            request_id=rid, prompt="p", prompt_token_ids=[1],
            prompt_logprobs=None,
            outputs=[CompletionOutput(0, " x", [2], 0.0, None,
                                      "stop" if finished else None)],
            finished=finished)

    def step(self):
        self.step_calls += 1
        outs = [self._emit(rid, True) for rid in self.requests]
        self.requests = []
        return outs

    def step_pipelined(self):
        # First call dispatches (returns nothing, keeps inflight), second
        # finalizes — models the dispatch/fetch split.
        self.step_calls += 1
        if self.requests and not self._inflight:
            self._inflight = len(self.requests)
            return []
        if self._inflight:
            outs = [self._emit(rid, True)
                    for rid in self.requests[:self._inflight]]
            self.requests = self.requests[:len(self.requests)
                                          - self._inflight]
            self._inflight = 0
            return outs
        return []


def _wrap(mock):
    eng = AsyncLLMEngine.__new__(AsyncLLMEngine)
    eng.engine = mock
    eng.log_requests = False
    eng.start_engine_loop = True
    eng.background_loop = None
    eng._background_loop_unshielded = None
    from intellillm_tpu.engine.async_llm_engine import RequestTracker
    eng._request_tracker = RequestTracker()
    eng._errored_with = None
    return eng


@pytest.mark.parametrize("pipeline", [False, True])
def test_loop_steps_then_idles(pipeline):
    async def run():
        mock = _MockEngine(pipeline)
        eng = _wrap(mock)
        stream = await eng.add_request("r1", prompt=None,
                                       sampling_params=None,
                                       prompt_token_ids=[1])
        out = await asyncio.wait_for(stream.__anext__(), timeout=10)
        assert out.finished
        calls_after_first = mock.step_calls
        await asyncio.sleep(0.2)
        # Idle: the loop must be parked on the new-request event, not
        # spinning the engine.
        assert mock.step_calls <= calls_after_first + 1

        stream2 = await eng.add_request("r2", prompt=None,
                                        sampling_params=None,
                                        prompt_token_ids=[1])
        out2 = await asyncio.wait_for(stream2.__anext__(), timeout=10)
        assert out2.finished

    asyncio.run(run())


def test_pipelined_inflight_keeps_loop_alive():
    """A step that returns no outputs but leaves work in flight must NOT
    park the loop (the fetch comes on the next call)."""
    async def run():
        mock = _MockEngine(pipeline=True)
        eng = _wrap(mock)
        stream = await eng.add_request("r1", prompt=None,
                                       sampling_params=None,
                                       prompt_token_ids=[1])
        # step 1 returns [] with inflight=1; without the has_inflight
        # condition the loop would wait for a new request forever.
        out = await asyncio.wait_for(stream.__anext__(), timeout=10)
        assert out.finished

    asyncio.run(run())
