"""Acceptance test for the engine stall watchdog, end to end on a real
CPU engine: wedge the engine by blocking a jitted dispatch past the
(dropped) threshold, observe the watchdog fire exactly once with a full
report at GET /debug/stall, see /health/detail flip to 503 — then
release the wedge and watch a completed step clear everything back to
200/ok.
"""
import asyncio
import threading
import time

from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from intellillm_tpu import LLM, SamplingParams
from intellillm_tpu.entrypoints.debug_routes import add_debug_routes
from intellillm_tpu.obs import (get_alert_manager, get_compile_tracker,
                                get_flight_recorder, get_metrics_history,
                                get_slo_tracker, get_watchdog)


def _get(app, *paths):
    """Serve `app` in-process and GET each path; returns a list of
    (status, json_body)."""
    async def go():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            out = []
            for path in paths:
                resp = await client.get(path)
                out.append((resp.status, await resp.json()))
            return out
        finally:
            await client.close()
    return asyncio.run(go())


def test_wedged_dispatch_fires_watchdog_and_health_detail(tiny_opt_dir):
    get_flight_recorder().reset_for_testing()
    get_slo_tracker().reset_for_testing()
    # /health/detail now consults the alert manager over the history
    # store: stale goodput points from earlier engine tests would read
    # as an SLO burn and report "degraded" where this test needs "ok".
    get_metrics_history().reset_for_testing()
    get_alert_manager().reset_for_testing()
    wd = get_watchdog()
    # Fresh watchdog BEFORE the engine builds: warm-up compiles run under
    # the default 300s dispatch threshold and must not trip anything.
    wd.reset_for_testing()

    llm = LLM(model=tiny_opt_dir, dtype="float32",
              num_device_blocks_override=128, max_model_len=128,
              max_num_seqs=8, max_paddings=512, swap_space=0.01)
    engine = llm.llm_engine

    def make_app():
        # A fresh Application per asyncio.run: aiohttp pins the app to
        # the first event loop it serves on.
        app = web.Application()
        add_debug_routes(app, lambda: engine)
        return app

    tracker = get_compile_tracker()
    orig_call = tracker.call  # bound method, survives the shadow below
    release = threading.Event()
    wedged = threading.Event()
    state = {"blocked": False}

    def blocked_call(program, key, fn, *args, **kwargs):
        # Wedge only the first dispatch; later ones (the drain after
        # release) go straight through.
        if not state["blocked"]:
            state["blocked"] = True
            wedged.set()
            release.wait(timeout=60.0)
        return orig_call(program, key, fn, *args, **kwargs)

    tracker.call = blocked_call
    runner = None
    try:
        engine.add_request("31", "hello my name is",
                           SamplingParams(temperature=0.0, max_tokens=8,
                                          ignore_eos=True))
        # Tight thresholds only now that warm-up is done: a dispatch
        # blocked > 0.2s is a stall, polled every 50ms. stall_s stays
        # high so only dispatch_blocked can fire.
        wd.configure(stall_s=30.0, dispatch_s=0.2, poll_s=0.05)
        runner = threading.Thread(target=llm._run_engine,
                                  kwargs={"use_tqdm": False},
                                  name="wedge-runner")
        runner.start()
        assert wedged.wait(timeout=30.0), "dispatch never reached"

        deadline = time.monotonic() + 10.0
        while wd.state != "stalled" and time.monotonic() < deadline:
            time.sleep(0.02)
        assert wd.state == "stalled", "watchdog never declared the stall"

        (health_status, health), (stall_status, stall) = _get(
            make_app(), "/health/detail", "/debug/stall")
        assert health_status == 503
        assert health["status"] == "stalled"
        assert health["watchdog"]["state"] == "stalled"
        assert health["queue_depths"] is not None

        assert stall_status == 200
        reports = stall["reports"]
        assert len(reports) == 1  # one-shot per episode
        report = reports[0]
        assert report["reason"] == "dispatch_blocked"
        assert report["detail"]["blocked_for_s"] >= 0.2
        assert report["queue_depths"] is not None
        assert "31" in report["live_request_ids"]
        assert "compile_tracker" in report
        # The report names the culprit: some thread is parked in our
        # wedge, visible in the faulthandler-style stack dump.
        assert any("blocked_call" in stack
                   for stack in report["thread_stacks"].values()), (
            list(report["thread_stacks"]))
    finally:
        # Restore a sane threshold BEFORE releasing: the drain will
        # compile fresh decode buckets, and a legitimate >0.2s CPU
        # compile would (correctly) fire a second episode.
        wd.configure(stall_s=60.0, dispatch_s=300.0)
        release.set()
        if runner is not None:
            runner.join(timeout=120.0)
            assert not runner.is_alive(), "engine never drained"
        del tracker.call  # un-shadow the bound method

    try:
        # The drain completed steps, which must have cleared the stall.
        assert wd.state == "ok"
        snap = wd.snapshot()
        assert snap["stalls_fired"] == 1
        (health_status, health), = _get(make_app(), "/health/detail")
        assert health_status == 200
        assert health["status"] == "ok"
        # The wedged request still finished and fed the SLO window.
        assert get_slo_tracker().summary()["window"] == 1
    finally:
        wd.reset_for_testing()
        get_flight_recorder().reset_for_testing()
        get_slo_tracker().reset_for_testing()
