"""E2e acceptance for the per-kernel cost ledger (obs/kernels.py): a
real CPU-backend engine run must create a ledger entry for every jit
bucket the runner dispatched — with the `mixed` program present after
one generate — and `GET /debug/kernels` must serve it on BOTH servers.
On CPU the degradation contract holds end to end: analysis fields are
null (not zero) because `auto` introspection skips the second compile
on the tier-1 backend."""
import asyncio

import pytest
from aiohttp.test_utils import TestClient, TestServer

from intellillm_tpu import LLM, SamplingParams
from intellillm_tpu.obs import get_kernel_ledger


@pytest.fixture
def fresh_kernels(monkeypatch):
    monkeypatch.delenv("INTELLILLM_KERNEL_INTROSPECT", raising=False)
    monkeypatch.delenv("INTELLILLM_KERNEL_LEDGER", raising=False)
    ledger = get_kernel_ledger()
    ledger.reset_for_testing()
    yield ledger
    ledger.reset_for_testing()


def _serve_and_fetch(build_app, path="/debug/kernels?top=16"):
    result = {}

    async def go():
        client = TestClient(TestServer(build_app()))
        await client.start_server()
        try:
            resp = await client.get(path)
            result["status"] = resp.status
            result["data"] = await resp.json()
        finally:
            await client.close()

    asyncio.run(go())
    return result


def test_engine_run_populates_kernel_ledger_and_both_servers(
        tiny_opt_dir, example_prompts, fresh_kernels):
    llm = LLM(model=tiny_opt_dir, dtype="float32", max_model_len=128,
              max_num_seqs=8, max_paddings=512)
    params = SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)
    for i, prompt in enumerate(example_prompts):
        llm.llm_engine.add_request(str(i), prompt, params)
    llm._run_engine(use_tqdm=False)

    ledger = fresh_kernels
    snap = ledger.snapshot(top=32)

    # Every dispatched jit bucket is a ledger entry; the run prefills
    # and decodes, so the mixed program must be there.
    assert snap["executables_total"] > 0
    programs = snap["programs"]
    assert "mixed" in programs, programs
    assert programs["mixed"]["executables"] >= 1
    assert programs["mixed"]["dispatches"] >= 1

    # CPU degradation contract: `auto` introspection skips the second
    # compile on the CPU backend, so every analysis field is null —
    # None, never 0 — while bookkeeping fields stay real.
    assert snap["backend"] == "cpu"
    assert snap["introspection"] == "auto"
    for entry in snap["executables"]:
        assert entry["analysis"] == "skipped"
        assert entry["flops"] is None
        assert entry["bytes_accessed"] is None
        assert entry["hbm_peak_bytes"] is None
        assert entry["dispatches"] >= 1
        assert entry["compile_seconds"] is not None
    assert programs["mixed"]["flops_max"] is None

    # The engine marked step boundaries; with no per-executable FLOPs
    # the cost-model MFU reads null (the analytic one rides along for
    # the cross-check).
    assert snap["steps"] > 0
    assert snap["mfu_costmodel"] is None
    assert "mfu_analytic" in snap

    # Both servers serve the same process-global ledger.
    from intellillm_tpu.entrypoints import api_server as demo_server
    from intellillm_tpu.entrypoints.openai import api_server as \
        openai_server
    for build_app in (demo_server.build_app, openai_server.build_app):
        served = _serve_and_fetch(build_app)
        assert served["status"] == 200
        data = served["data"]
        assert data["executables_total"] == snap["executables_total"]
        assert data["programs"]["mixed"]["dispatches"] == \
            programs["mixed"]["dispatches"]
        assert data["executables"][0]["flops"] is None

    # /health/detail carries the compact block (no per-executable list).
    served = _serve_and_fetch(demo_server.build_app, "/health/detail")
    kernels = served["data"]["kernels"]
    assert kernels["executables_total"] == snap["executables_total"]
    assert "executables" not in kernels
