"""End-to-end preemption correctness: a memory-pressured engine must
produce EXACTLY the outputs of an unpressured one.

Reference role: the recompute/swap preemption paths
(`core/scheduler.py:_preempt*`) are only scheduler-unit-tested; these
tests drive them through the full engine and assert token equality —
recompute must regenerate identical prefixes and swap must restore KV
bit-exactly.
"""
import pytest

from intellillm_tpu import LLM, SamplingParams


def _llm(model_dir, blocks, **kw):
    return LLM(model=model_dir, dtype="float32",
               num_device_blocks_override=blocks, max_model_len=128,
               max_num_seqs=8, max_paddings=512, swap_space=0.01, **kw)


def _generate(llm, prompts, params_list):
    engine = llm.llm_engine
    for i, (p, sp) in enumerate(zip(prompts, params_list)):
        engine.add_request(str(i), p, sp)
    outs = {o.request_id: o for o in llm._run_engine(use_tqdm=False)}
    return [outs[str(i)] for i in range(len(prompts))]


def test_recompute_preemption_preserves_greedy(tiny_opt_dir,
                                               example_prompts,
                                               monkeypatch):
    """Pool of 10 blocks vs 4 seqs needing ~4 blocks each at peak: the
    scheduler must preempt by recompute; outputs must equal the
    unpressured run's — and preemption must actually have happened."""
    from intellillm_tpu.core import scheduler as sched_mod

    params = [SamplingParams(temperature=0.0, max_tokens=48,
                             ignore_eos=True)
              for _ in example_prompts]

    roomy = _generate(_llm(tiny_opt_dir, 128), example_prompts, params)

    preemptions = {"n": 0}
    orig = sched_mod.Scheduler._preempt_by_recompute

    def counting(self, seq_group):
        preemptions["n"] += 1
        return orig(self, seq_group)

    monkeypatch.setattr(sched_mod.Scheduler, "_preempt_by_recompute",
                        counting)
    tight = _generate(_llm(tiny_opt_dir, 10), example_prompts, params)

    assert preemptions["n"] > 0, (
        "pool was sized to force recompute preemption but none happened")
    for i, (r, t) in enumerate(zip(roomy, tight)):
        assert r.outputs[0].token_ids == t.outputs[0].token_ids, (
            f"prompt {i} diverged under preemption")


def test_swap_preemption_preserves_outputs(tiny_opt_dir, example_prompts,
                                           monkeypatch):
    """best_of=2 groups preempt by SWAP (multi-seq state can't recompute);
    swapped-and-restored KV must reproduce the unpressured outputs, and
    the swap path must actually have run."""
    from intellillm_tpu.worker import cache_engine as ce

    params = [SamplingParams(temperature=0.8, best_of=2, n=2,
                             max_tokens=40, ignore_eos=True)
              for _ in example_prompts]

    roomy = _generate(_llm(tiny_opt_dir, 128), example_prompts, params)

    swaps = {"out": 0, "in": 0}
    orig_out = ce.CacheEngine.swap_out
    orig_in = ce.CacheEngine.swap_in

    def counting_out(self, mapping):
        swaps["out"] += 1
        return orig_out(self, mapping)

    def counting_in(self, mapping):
        swaps["in"] += 1
        return orig_in(self, mapping)

    monkeypatch.setattr(ce.CacheEngine, "swap_out", counting_out)
    monkeypatch.setattr(ce.CacheEngine, "swap_in", counting_in)

    tight = _generate(_llm(tiny_opt_dir, 14), example_prompts, params)

    assert swaps["out"] > 0 and swaps["in"] > 0, (
        "pool was sized to force swap preemption but none happened — "
        f"swaps={swaps}")
    for i, (r, t) in enumerate(zip(roomy, tight)):
        r_tok = sorted(c.token_ids for c in r.outputs)
        t_tok = sorted(c.token_ids for c in t.outputs)
        assert r_tok == t_tok, f"prompt {i} diverged under swap"


# --- chunked prefill: preemption of partially-prefilled sequences -------

_LONG_PROMPTS = [
    " ".join(["the cat runs fast and the dog"] * 7),      # 49 tokens
    " ".join(["the president of the united states is"] * 6),  # 42 tokens
    " ".join(["the capital of france is paris"] * 7),     # 42 tokens
    " ".join(["hello my name is"] * 10),                  # 40 tokens
]


def test_chunked_recompute_preemption_preserves_greedy(tiny_opt_dir,
                                                       monkeypatch):
    """Chunked prefill + tight pool: recompute preemption must hit at
    least one PARTIALLY-prefilled group (num_computed_tokens mid-prompt),
    and the re-chunked re-prefill must reproduce the unpressured chunked
    run's tokens exactly."""
    from intellillm_tpu.core import scheduler as sched_mod

    params = [SamplingParams(temperature=0.0, max_tokens=24,
                             ignore_eos=True)
              for _ in _LONG_PROMPTS]
    chunked_kw = dict(enable_chunked_prefill=True,
                      max_num_batched_tokens=16)

    roomy = _generate(_llm(tiny_opt_dir, 128, **chunked_kw),
                      _LONG_PROMPTS, params)

    hits = {"total": 0, "mid_chunk": 0}
    orig = sched_mod.Scheduler._preempt_by_recompute

    def counting(self, seq_group):
        hits["total"] += 1
        if any(not s.data.prefill_complete
               for s in seq_group.get_unfinished_seqs()):
            hits["mid_chunk"] += 1
        return orig(self, seq_group)

    monkeypatch.setattr(sched_mod.Scheduler, "_preempt_by_recompute",
                        counting)
    tight = _generate(_llm(tiny_opt_dir, 10, **chunked_kw),
                      _LONG_PROMPTS, params)

    assert hits["mid_chunk"] > 0, (
        "pool was sized so recompute preemption hits a mid-chunk group "
        f"but none did — hits={hits}")
    for i, (r, t) in enumerate(zip(roomy, tight)):
        assert r.outputs[0].token_ids == t.outputs[0].token_ids, (
            f"prompt {i} diverged under mid-chunk recompute preemption")


def test_chunked_swap_preemption_preserves_greedy(tiny_opt_dir,
                                                  monkeypatch):
    """Force SWAP preemption (instead of the single-seq recompute
    default) under chunked prefill: a swapped-out mid-chunk group keeps
    its num_computed_tokens, and swap-in must resume chunking exactly
    where the KV left off — outputs must match the unpressured run."""
    from intellillm_tpu.core import scheduler as sched_mod
    from intellillm_tpu.worker import cache_engine as ce

    params = [SamplingParams(temperature=0.0, max_tokens=24,
                             ignore_eos=True)
              for _ in _LONG_PROMPTS]
    chunked_kw = dict(enable_chunked_prefill=True,
                      max_num_batched_tokens=16)

    roomy = _generate(_llm(tiny_opt_dir, 128, **chunked_kw),
                      _LONG_PROMPTS, params)

    hits = {"swap_out": 0, "swap_in": 0, "mid_chunk": 0}
    orig_preempt = sched_mod.Scheduler._preempt

    def forced_swap(self, seq_group, blocks_to_swap_out,
                    preemption_mode=None):
        if any(not s.data.prefill_complete
               for s in seq_group.get_unfinished_seqs()):
            hits["mid_chunk"] += 1
        return orig_preempt(self, seq_group, blocks_to_swap_out,
                            sched_mod.PreemptionMode.SWAP)

    orig_out = ce.CacheEngine.swap_out
    orig_in = ce.CacheEngine.swap_in

    def counting_out(self, mapping):
        hits["swap_out"] += 1
        return orig_out(self, mapping)

    def counting_in(self, mapping):
        hits["swap_in"] += 1
        return orig_in(self, mapping)

    monkeypatch.setattr(sched_mod.Scheduler, "_preempt", forced_swap)
    monkeypatch.setattr(ce.CacheEngine, "swap_out", counting_out)
    monkeypatch.setattr(ce.CacheEngine, "swap_in", counting_in)

    tight = _generate(_llm(tiny_opt_dir, 10, **chunked_kw),
                      _LONG_PROMPTS, params)

    assert hits["swap_out"] > 0 and hits["swap_in"] > 0, (
        f"pool was sized to force swap preemption but none ran — {hits}")
    assert hits["mid_chunk"] > 0, (
        "no swap preemption hit a mid-chunk group — the resume-from-"
        f"num_computed_tokens path went unexercised — {hits}")
    for i, (r, t) in enumerate(zip(roomy, tight)):
        assert r.outputs[0].token_ids == t.outputs[0].token_ids, (
            f"prompt {i} diverged under mid-chunk swap preemption")
