"""End-to-end preemption correctness: a memory-pressured engine must
produce EXACTLY the outputs of an unpressured one.

Reference role: the recompute/swap preemption paths
(`core/scheduler.py:_preempt*`) are only scheduler-unit-tested; these
tests drive them through the full engine and assert token equality —
recompute must regenerate identical prefixes and swap must restore KV
bit-exactly.
"""
import pytest

from intellillm_tpu import LLM, SamplingParams


def _llm(model_dir, blocks, **kw):
    return LLM(model=model_dir, dtype="float32",
               num_device_blocks_override=blocks, max_model_len=128,
               max_num_seqs=8, max_paddings=512, swap_space=0.01, **kw)


def _generate(llm, prompts, params_list):
    engine = llm.llm_engine
    for i, (p, sp) in enumerate(zip(prompts, params_list)):
        engine.add_request(str(i), p, sp)
    outs = {o.request_id: o for o in llm._run_engine(use_tqdm=False)}
    return [outs[str(i)] for i in range(len(prompts))]


def test_recompute_preemption_preserves_greedy(tiny_opt_dir,
                                               example_prompts,
                                               monkeypatch):
    """Pool of 10 blocks vs 4 seqs needing ~4 blocks each at peak: the
    scheduler must preempt by recompute; outputs must equal the
    unpressured run's — and preemption must actually have happened."""
    from intellillm_tpu.core import scheduler as sched_mod

    params = [SamplingParams(temperature=0.0, max_tokens=48,
                             ignore_eos=True)
              for _ in example_prompts]

    roomy = _generate(_llm(tiny_opt_dir, 128), example_prompts, params)

    preemptions = {"n": 0}
    orig = sched_mod.Scheduler._preempt_by_recompute

    def counting(self, seq_group):
        preemptions["n"] += 1
        return orig(self, seq_group)

    monkeypatch.setattr(sched_mod.Scheduler, "_preempt_by_recompute",
                        counting)
    tight = _generate(_llm(tiny_opt_dir, 10), example_prompts, params)

    assert preemptions["n"] > 0, (
        "pool was sized to force recompute preemption but none happened")
    for i, (r, t) in enumerate(zip(roomy, tight)):
        assert r.outputs[0].token_ids == t.outputs[0].token_ids, (
            f"prompt {i} diverged under preemption")


def test_swap_preemption_preserves_outputs(tiny_opt_dir, example_prompts,
                                           monkeypatch):
    """best_of=2 groups preempt by SWAP (multi-seq state can't recompute);
    swapped-and-restored KV must reproduce the unpressured outputs, and
    the swap path must actually have run."""
    from intellillm_tpu.worker import cache_engine as ce

    params = [SamplingParams(temperature=0.8, best_of=2, n=2,
                             max_tokens=40, ignore_eos=True)
              for _ in example_prompts]

    roomy = _generate(_llm(tiny_opt_dir, 128), example_prompts, params)

    swaps = {"out": 0, "in": 0}
    orig_out = ce.CacheEngine.swap_out
    orig_in = ce.CacheEngine.swap_in

    def counting_out(self, mapping):
        swaps["out"] += 1
        return orig_out(self, mapping)

    def counting_in(self, mapping):
        swaps["in"] += 1
        return orig_in(self, mapping)

    monkeypatch.setattr(ce.CacheEngine, "swap_out", counting_out)
    monkeypatch.setattr(ce.CacheEngine, "swap_in", counting_in)

    tight = _generate(_llm(tiny_opt_dir, 14), example_prompts, params)

    assert swaps["out"] > 0 and swaps["in"] > 0, (
        "pool was sized to force swap preemption but none happened — "
        f"swaps={swaps}")
    for i, (r, t) in enumerate(zip(roomy, tight)):
        r_tok = sorted(c.token_ids for c in r.outputs)
        t_tok = sorted(c.token_ids for c in t.outputs)
        assert r_tok == t_tok, f"prompt {i} diverged under swap"
