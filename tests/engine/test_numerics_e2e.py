"""Numerics observability through a real CPU engine — the PR's
acceptance criteria:

- default-off is invisible: no numerics-marked executables, zero rows
  checked, and greedy outputs identical to an enabled-mode clean run
  (the sentinel panel must never perturb sampling);
- a forced in-graph NaN (`inject_nan` testing hook) quarantines the
  poisoned request with a structured abort, a `numerics_anomaly`
  flight event in the sealed trace, and an active page alert — while
  co-scheduled requests finish normally;
- a byte flipped in the host swap pool between swap-out and swap-in is
  caught by the sampled KV-integrity audit.
"""
import numpy as np
import pytest

from intellillm_tpu import LLM, SamplingParams
from intellillm_tpu.obs import get_compile_tracker, get_flight_recorder
from intellillm_tpu.obs import numerics as numerics_mod
from intellillm_tpu.obs.alerts import (KVIntegrityMismatchRule,
                                       NumericsAnomalyRule)
from intellillm_tpu.obs.numerics import (get_kv_audit,
                                         get_numerics_tracker)

PROMPTS = ["hello my name is", "the capital of france is"]


@pytest.fixture
def fresh_numerics():
    numerics_mod.reset_for_testing()
    get_compile_tracker().reset_for_testing()
    get_flight_recorder().reset_for_testing()
    yield
    numerics_mod.reset_for_testing()
    get_flight_recorder().reset_for_testing()


def _build(tiny_opt_dir):
    return LLM(model=tiny_opt_dir, dtype="float32",
               num_device_blocks_override=128, max_model_len=128,
               max_num_seqs=8, max_paddings=512, swap_space=0.01)


def _greedy_tokens(llm, max_tokens=12):
    engine = llm.llm_engine
    params = SamplingParams(temperature=0.0, max_tokens=max_tokens,
                            ignore_eos=True)
    for i, prompt in enumerate(PROMPTS):
        engine.add_request(str(i), prompt, params)
    outs = llm._run_engine(use_tqdm=False)
    return {o.request_id: list(o.outputs[0].token_ids) for o in outs}


def test_default_off_adds_no_executables_and_enabled_matches(tiny_opt_dir,
                                                             fresh_numerics):
    # Default-off engine: the dispatch passes no numerics kwargs at all,
    # so the jit call structure — and therefore every compiled
    # executable — is bit-identical to the pre-numerics engine.
    tracker = get_numerics_tracker()
    assert tracker.enabled is False
    llm = _build(tiny_opt_dir)
    baseline = _greedy_tokens(llm)
    assert all(len(t) == 12 for t in baseline.values())
    snap_off = get_compile_tracker().snapshot()
    assert snap_off["compiles"], snap_off
    # Zero numerics-marked executables: every jit bucket key dispatched
    # by the default-off run is exactly the pre-sentinel key shape.
    mixed_keys = get_compile_tracker()._keys.get("mixed", set())
    assert mixed_keys
    assert not any("numerics" in key for key in mixed_keys), (
        f"default-off run compiled numerics variants: {mixed_keys}")
    assert tracker.snapshot()["rows_checked"] == 0
    del llm

    # Enabled engine, same prompts: the sentinel panel rides along as an
    # extra device output under numerics-marked bucket keys, every row
    # is checked, and the greedy tokens are unchanged — the sentinels
    # observe the logits, they never modify them.
    get_compile_tracker().reset_for_testing()
    tracker.configure(enabled=True)
    llm = _build(tiny_opt_dir)
    enabled = _greedy_tokens(llm)
    assert enabled == baseline, (
        "enabling numerics sentinels changed greedy outputs")
    mixed_keys = get_compile_tracker()._keys.get("mixed", set())
    assert any("numerics" in key for key in mixed_keys), mixed_keys
    snap = tracker.snapshot()
    assert snap["rows_checked"] > 0
    assert sum(snap["anomalies"].values()) == 0
    assert snap["last_step"]["mean_top1_prob"] is not None


def test_forced_nan_quarantines_alerts_and_traces(tiny_opt_dir,
                                                  fresh_numerics):
    tracker = get_numerics_tracker()
    tracker.configure(enabled=True)
    llm = _build(tiny_opt_dir)
    engine = llm.llm_engine
    params = SamplingParams(temperature=0.0, max_tokens=12, ignore_eos=True)
    # "0" is the victim, "1" the co-scheduled bystander (_run_engine
    # sorts outputs by integer request id).
    engine.add_request("0", PROMPTS[0], params)
    engine.add_request("1", PROMPTS[1], params)
    # The next dispatched step carrying request "0" gets NaN added to
    # its logit row IN-GRAPH — the full device→sentinel→quarantine path
    # runs, nothing is simulated host-side.
    tracker.inject_nan("0")
    outs = {o.request_id: o for o in llm._run_engine(use_tqdm=False)}

    victim = outs["0"]
    assert victim.finished
    assert victim.outputs[0].finish_reason == "abort"
    # Quarantined before streaming: the poisoned token never landed.
    assert len(victim.outputs[0].token_ids) == 0
    # The co-scheduled request is untouched.
    assert outs["1"].outputs[0].finish_reason == "length"
    assert len(outs["1"].outputs[0].token_ids) == 12

    # The sealed trace explains WHY: numerics_anomaly ahead of the
    # abort terminal.
    trace = get_flight_recorder().get_trace("0")
    events = [e["event"] for e in trace]
    assert "numerics_anomaly" in events
    assert events.index("numerics_anomaly") < events.index("finished")
    anomaly = trace[events.index("numerics_anomaly")]
    assert "nan" in (anomaly.get("detail") or "")
    assert trace[events.index("finished")]["detail"] == "abort"

    snap = tracker.snapshot()
    assert snap["anomalies"]["nan"] >= 1
    assert snap["quarantined"] >= 1
    assert snap["last_anomaly"]["request_id"] == "0"

    # ...and the page-severity rule is active on the fresh anomaly.
    active, _, detail = NumericsAnomalyRule(window_s=600.0).evaluate(
        None, now=0.0)
    assert active is True, detail


def test_host_pool_byte_flip_is_caught_at_swap_in(tiny_opt_dir,
                                                  fresh_numerics):
    audit = get_kv_audit()
    audit.configure(enabled=True, sample=1.0)
    llm = _build(tiny_opt_dir)
    # Prefill something so device blocks hold real (nonzero) KV.
    _greedy_tokens(llm, max_tokens=4)
    cache_engine = llm.llm_engine.worker.cache_engine

    cache_engine.swap_out({0: 1, 1: 2})
    snap = audit.snapshot()
    assert snap["checksums"]["swap_out"] == 2 * cache_engine.num_layers

    # Corruption strikes host block 1 while it sits in CPU memory.
    k_cpu, _v_cpu = cache_engine.cpu_cache[0]
    k_cpu[1].view(np.uint8).reshape(-1)[5] ^= 0x01

    cache_engine.swap_in({1: 0, 2: 1})
    snap = audit.snapshot()
    assert snap["checksums"]["swap_in"] == 2 * cache_engine.num_layers
    assert snap["mismatches"]["swap_in"] == 1
    assert snap["last_mismatch"]["layer"] == 0
    assert snap["last_mismatch"]["block"] == 1

    active, _, detail = KVIntegrityMismatchRule(window_s=600.0).evaluate(
        None, now=0.0)
    assert active is True, detail
