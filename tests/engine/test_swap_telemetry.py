"""Swap-byte accounting through a real engine: a forced swap-out/
swap-in cycle must increment both `intellillm_swap_bytes_total`
directions (in block-byte multiples) and leave matching swapped_out/
swapped_in events in the flight recorder — the PR's acceptance
criterion for the memory telemetry wiring."""
import pytest

from intellillm_tpu import LLM, SamplingParams
from intellillm_tpu.obs import get_device_telemetry, get_flight_recorder


@pytest.fixture
def fresh_telemetry():
    telemetry = get_device_telemetry()
    recorder = get_flight_recorder()
    telemetry.reset_for_testing()
    recorder.reset_for_testing()
    yield telemetry
    telemetry.reset_for_testing()
    recorder.reset_for_testing()


def test_forced_swap_cycle_accounts_bytes_and_events(tiny_opt_dir,
                                                     example_prompts,
                                                     fresh_telemetry):
    # 14-block pool + best_of=2 groups: multi-seq state cannot recompute,
    # so the scheduler must preempt by SWAP (same recipe as
    # test_preemption_e2e::test_swap_preemption_preserves_outputs).
    llm = LLM(model=tiny_opt_dir, dtype="float32",
              num_device_blocks_override=14, max_model_len=128,
              max_num_seqs=8, max_paddings=512, swap_space=0.01)
    engine = llm.llm_engine
    params = SamplingParams(temperature=0.8, best_of=2, n=2,
                            max_tokens=40, ignore_eos=True)
    for i, prompt in enumerate(example_prompts):
        engine.add_request(str(i), prompt, params)
    llm._run_engine(use_tqdm=False)

    telemetry = fresh_telemetry
    totals = telemetry.swap_bytes_total()
    assert totals["out"] > 0 and totals["in"] > 0, totals

    # Byte totals must be whole multiples of the host-payload block size.
    block_bytes = llm.llm_engine.worker.cache_engine.logical_block_bytes
    assert block_bytes > 0
    assert totals["out"] % block_bytes == 0
    assert totals["in"] % block_bytes == 0
    # Everything swapped out was swapped back in (all requests finished).
    assert totals["in"] <= totals["out"]

    # Matching per-request flight-recorder events.
    events = [e["event"]
              for trace in get_flight_recorder().recent_finished(64)
              for e in trace["events"]]
    assert "swapped_out" in events
    assert "swapped_in" in events

    # The engine installed a non-empty ledger at init.
    ledger = telemetry.ledger()
    assert ledger.get("params", 0) > 0
    assert ledger.get("kv_pool", 0) > 0
    snap = telemetry.snapshot()
    assert snap["devices"], "poller must have sampled at least once"
