"""Stop handling under fused multi-step decode.

Stop strings / stop tokens must produce identical results whether the
engine fuses K decode steps or runs them one at a time (the engine
discards overshoot tokens past the stop, so fused K stays enabled for
stop-bearing batches — VERDICT round-1 weak #6)."""
import pytest

from intellillm_tpu import LLM, SamplingParams


def _run(model_dir, prompts, params_list, num_decode_steps):
    llm = LLM(model=model_dir, dtype="float32",
              num_device_blocks_override=128, max_model_len=128,
              max_num_seqs=8, max_paddings=512, swap_space=0.01,
              num_decode_steps=num_decode_steps)
    engine = llm.llm_engine
    for i, (prompt, params) in enumerate(zip(prompts, params_list)):
        engine.add_request(str(i), prompt, params)
    outs = llm._run_engine(use_tqdm=False)
    return [(o.outputs[0].token_ids, o.outputs[0].text,
             o.outputs[0].finish_reason) for o in outs]


def test_stop_string_fused_matches_unfused(tiny_opt_dir, example_prompts):
    # Greedy tiny-OPT repeats tokens, so use the first generated word as a
    # stop string — it triggers mid-stream deterministically.
    probe = _run(tiny_opt_dir, example_prompts[:1],
                 [SamplingParams(temperature=0.0, max_tokens=4)], 1)
    first_word = probe[0][1].strip().split()[0]

    params = [
        SamplingParams(temperature=0.0, max_tokens=24, stop=[first_word]),
        SamplingParams(temperature=0.0, max_tokens=24),
        SamplingParams(temperature=0.0, max_tokens=24,
                       stop_token_ids=[probe[0][0][0]]),
        SamplingParams(temperature=0.0, max_tokens=24),
    ]
    ref = _run(tiny_opt_dir, example_prompts, params, 1)
    got = _run(tiny_opt_dir, example_prompts, params, 8)
    assert got == ref
    # The stop actually triggered (not just length-capped).
    assert ref[0][2] == "stop"
    assert ref[2][2] == "stop"


def test_mixed_stop_and_plain_requests_fused(tiny_llama_dir,
                                             example_prompts):
    """A batch mixing stop-bearing and plain requests completes with the
    same outputs fused and unfused."""
    params = [SamplingParams(temperature=0.0, max_tokens=16,
                             stop=["the"] if i % 2 == 0 else [])
              for i in range(len(example_prompts))]
    ref = _run(tiny_llama_dir, example_prompts, params, 1)
    got = _run(tiny_llama_dir, example_prompts, params, 8)
    assert got == ref


@pytest.mark.parametrize("num_steps", [32, 20])
def test_chunked_fused_decode_matches_unfused(tiny_llama_dir,
                                              example_prompts,
                                              monkeypatch, num_steps):
    """Fused decode with C=8 chunks (K=32 → 4 full chunks; K=20 → 2 full
    + a 4-step tail chunk) must match single-step decode token-for-token
    — covers the chunk-boundary pool-context advance, the per-chunk
    page commit, and the non-divisible tail schedule."""
    monkeypatch.setenv("INTELLILLM_DECODE_CHUNK", "8")
    params = [SamplingParams(temperature=0.0, max_tokens=24,
                             ignore_eos=True)
              for _ in example_prompts]
    ref = _run(tiny_llama_dir, example_prompts, params, 1)
    got = _run(tiny_llama_dir, example_prompts, params, num_steps)
    assert got == ref


def test_fused_decode_near_max_model_len(tiny_llama_dir):
    """A long prompt decoding up to max_model_len under fused K must not
    overflow the block-table width buckets: the K-slot lookahead used to
    reserve len+K-1 slots unclamped, which for len close to max_model_len
    exceeded ceil(max_model_len/block_size) blocks and crashed batch
    prep ('block table of N blocks exceeds padded width W')."""
    from intellillm_tpu import LLM, SamplingParams

    llm = LLM(model=tiny_llama_dir, dtype="float32",
              num_device_blocks_override=64, max_model_len=128,
              max_num_seqs=4, max_paddings=512, swap_space=0.01,
              num_decode_steps=32)
    engine = llm.llm_engine
    prompt_ids = list(range(2, 110))          # 108 tokens; 108+32-1 > 128
    engine.add_request("0", None,
                       SamplingParams(temperature=0.0, max_tokens=64,
                                      ignore_eos=True),
                       prompt_token_ids=prompt_ids)
    outs = llm._run_engine(use_tqdm=False)
    assert outs[0].outputs[0].finish_reason == "length"
    # Reference parity: _check_stop fires when get_len() EXCEEDS
    # max_model_len (after the append), so 128 - 108 + 1 = 21 tokens —
    # identical under K=1 and fused K (verified both).
    assert len(outs[0].outputs[0].token_ids) == 21


def test_near_cap_tight_pool_no_preemption_livelock(tiny_llama_dir):
    """Admission checks must use the SAME clamped K-slot lookahead as the
    reservation: with a pool that fits the near-cap sequence but not the
    unclamped K budget, an unclamped can_append_slots preempts the group
    on every decode pass, degrading to one full re-prefill per token
    (measured: >= 9 engine steps for 8 tokens). With the clamp the whole
    request completes in prefill + one fused-K call."""
    from intellillm_tpu import LLM, SamplingParams

    llm = LLM(model=tiny_llama_dir, dtype="float32",
              num_device_blocks_override=10, max_model_len=128,
              max_num_seqs=2, max_paddings=512, swap_space=0.01,
              num_decode_steps=32)
    engine = llm.llm_engine
    engine.add_request("0", None,
                       SamplingParams(temperature=0.0, max_tokens=8,
                                      ignore_eos=True),
                       prompt_token_ids=[2, 3, 4, 5] * 30)  # 120 tokens
    finished = None
    steps = 0
    for _ in range(40):
        steps += 1
        for out in engine.step():
            if out.finished:
                finished = out
        if finished:
            break
    assert finished is not None, "engine made no progress (preempt loop)"
    assert len(finished.outputs[0].token_ids) >= 8
    assert steps <= 4, (
        f"took {steps} engine steps for 8 tokens — the per-token "
        "preempt/re-prefill pathology is back")


def test_penalties_e2e_change_output(tiny_opt_dir, example_prompts):
    """Greedy + strong repetition penalty must diverge from plain greedy
    (tiny-OPT repeats tokens) and produce no repeated immediate bigrams of
    the same token beyond what the penalty allows — smoke check that the
    device-side penalty path is live."""
    plain = _run(tiny_opt_dir, example_prompts[:1],
                 [SamplingParams(temperature=0.0, max_tokens=12)], 1)
    pen = _run(tiny_opt_dir, example_prompts[:1],
               [SamplingParams(temperature=0.0, max_tokens=12,
                               repetition_penalty=2.0)], 1)
    assert plain[0][0] != pen[0][0]
