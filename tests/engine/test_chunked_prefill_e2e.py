"""Chunked-prefill end-to-end acceptance on the CPU backend.

The headline property: turning --enable-chunked-prefill on (with a
budget small enough to force real chunk splits and mixed steps) must
not change a single greedy token versus the legacy homogeneous
scheduler for the same requests. Plus the legacy-mode guard: a mixed
metadata list WITHOUT chunk metadata must be rejected loudly instead of
silently batching under the first entry's phase.
"""
import pytest

from intellillm_tpu import LLM, SamplingParams
from intellillm_tpu.sequence import SequenceData, SequenceGroupMetadata

PROMPTS = [
    "hello my name is",
    "the president of the united states is",
    "the capital of france is",
    "the cat runs fast and the dog",
    " ".join(["the cat runs fast and the dog"] * 5),  # 35 tokens
]


def _generate(llm, prompts, params_list):
    engine = llm.llm_engine
    for i, (p, sp) in enumerate(zip(prompts, params_list)):
        engine.add_request(str(i), p, sp)
    outs = {o.request_id: o for o in llm._run_engine(use_tqdm=False)}
    return [outs[str(i)] for i in range(len(prompts))]


def _llm(model_dir, **kw):
    return LLM(model=model_dir, dtype="float32",
               num_device_blocks_override=128, max_model_len=128,
               max_num_seqs=8, max_paddings=512, **kw)


def test_chunked_on_matches_legacy_greedy(tiny_opt_dir):
    """Same requests, chunked on vs off: greedy outputs must be
    identical token for token. The tiny budget (8) forces multi-step
    chunk splits AND steps that mix decode rows with prefill chunks."""
    params = [SamplingParams(temperature=0.0, max_tokens=16,
                             ignore_eos=True) for _ in PROMPTS]

    legacy = _generate(_llm(tiny_opt_dir), PROMPTS, params)

    from intellillm_tpu.core import scheduler as sched_mod
    mixed_steps = {"n": 0, "split": 0}
    orig = sched_mod.Scheduler._chunked_pass

    def spy(self, now):
        out = orig(self, now)
        mixed_steps["n"] += 1
        if any(start > 0 for start, _, _ in out.chunked_prefills.values()):
            mixed_steps["split"] += 1
        return out

    sched_mod.Scheduler._chunked_pass = spy
    try:
        chunked = _generate(
            _llm(tiny_opt_dir, enable_chunked_prefill=True,
                 max_num_batched_tokens=8), PROMPTS, params)
    finally:
        sched_mod.Scheduler._chunked_pass = orig

    assert mixed_steps["n"] > 0, "chunked engine never took the mixed path"
    assert mixed_steps["split"] > 0, (
        "budget was sized to split prompts across steps but none split")
    for i, (l, c) in enumerate(zip(legacy, chunked)):
        assert l.outputs[0].token_ids == c.outputs[0].token_ids, (
            f"prompt {i}: chunked-on diverged from legacy "
            f"({l.outputs[0].token_ids} vs {c.outputs[0].token_ids})")


def test_chunked_off_is_default_and_identical(tiny_opt_dir):
    """The flag is off by default, and passing it explicitly as False is
    output-identical to not passing it at all (legacy golden)."""
    params = [SamplingParams(temperature=0.0, max_tokens=8,
                             ignore_eos=True) for _ in PROMPTS[:3]]
    implicit = _generate(_llm(tiny_opt_dir), PROMPTS[:3], params)
    explicit = _generate(_llm(tiny_opt_dir, enable_chunked_prefill=False),
                         PROMPTS[:3], params)
    for l, c in zip(implicit, explicit):
        assert l.outputs[0].token_ids == c.outputs[0].token_ids


def test_mixed_metadata_without_chunk_info_raises(tiny_opt_dir):
    """Legacy-mode guard: a metadata list mixing prefill and decode
    entries with no token_chunk_size must raise instead of silently
    batching everything under the first entry's phase."""
    llm = _llm(tiny_opt_dir)
    runner = llm.llm_engine.worker.model_runner
    sp = SamplingParams(temperature=0.0, max_tokens=4)

    def meta(rid, seq_id, is_prompt):
        return SequenceGroupMetadata(
            request_id=rid, is_prompt=is_prompt,
            seq_data={seq_id: SequenceData([3, 4, 5])},
            sampling_params=sp, block_tables={seq_id: [0]})

    caches = llm.llm_engine.worker.cache_engine.device_cache
    with pytest.raises(ValueError, match="chunked-prefill"):
        runner.execute_model([meta("0", 0, True), meta("1", 1, False)],
                             caches)
