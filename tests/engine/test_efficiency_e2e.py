"""E2e acceptance for the compute-efficiency ledger (obs/efficiency.py):
a real CPU-backend engine run must populate real/pad token totals,
per-axis fill ratios, and top-waste bucket pairs; `/debug/efficiency`
must serve them on BOTH servers; `intellillm_mfu` must degrade to NaN
(not 0) on CPU and turn finite once `INTELLILLM_PEAK_FLOPS` supplies a
denominator."""
import asyncio
import math

import pytest
from aiohttp.test_utils import TestClient, TestServer

from intellillm_tpu import LLM, SamplingParams
from intellillm_tpu.obs import get_efficiency_tracker


@pytest.fixture
def fresh_efficiency():
    tracker = get_efficiency_tracker()
    tracker.reset_for_testing()
    yield tracker
    tracker.reset_for_testing()


def _serve_and_fetch(build_app, path="/debug/efficiency"):
    result = {}

    async def go():
        client = TestClient(TestServer(build_app()))
        await client.start_server()
        try:
            resp = await client.get(path)
            result["status"] = resp.status
            result["data"] = await resp.json()
        finally:
            await client.close()

    asyncio.run(go())
    return result


def test_engine_run_populates_ledger_and_both_servers(
        tiny_opt_dir, example_prompts, fresh_efficiency, monkeypatch):
    monkeypatch.delenv("INTELLILLM_PEAK_FLOPS", raising=False)
    llm = LLM(model=tiny_opt_dir, dtype="float32", max_model_len=128,
              max_num_seqs=8, max_paddings=512)
    params = SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)
    for i, prompt in enumerate(example_prompts):
        llm.llm_engine.add_request(str(i), prompt, params)
    llm._run_engine(use_tqdm=False)

    tracker = fresh_efficiency
    snap = tracker.snapshot()

    # Token totals, split real vs pad per phase: prompts are shorter
    # than the padded len bucket, so prefill must carry pad tokens.
    tok = snap["tokens_total"]
    assert tok["prefill"]["real"] > 0
    assert tok["prefill"]["pad"] > 0
    assert tok["decode"]["real"] > 0
    assert snap["pad_fraction"] is not None and 0 < snap["pad_fraction"] < 1

    # Per-axis fill ratios: batch for prefill (chunk rows are one token
    # per row of the flat mixed batch — there is no padded len axis any
    # more), batch + block width for decode.
    fills = snap["fill_ratio_avg"]
    assert 0 < fills["prefill"]["batch"] <= 1
    assert fills["prefill"]["len"] is None
    assert 0 < fills["decode"]["batch"] <= 1
    assert 0 < fills["decode"]["block_width"] <= 1

    # Waste attribution per (batch bucket, len/width bucket) pair.
    assert snap["top_waste"], snap
    worst = snap["top_waste"][0]
    assert worst["batch_bucket"] > 0 and worst["inner_bucket"] > 0
    assert worst["axis"] in ("len", "block_width")

    # MFU: the engine stepped and derived a FLOPs model, but CPU has no
    # peak-FLOPs entry -> None in JSON, NaN (never 0) on the gauge.
    assert snap["steps"] > 0
    assert snap["flops_per_token"] and snap["flops_per_token"] > 0
    assert snap["peak_flops"] is None
    assert snap["mfu"] is None
    if tracker._metrics is not None:
        assert math.isnan(tracker._metrics.gauge_mfu._value.get())

    # Warm-up exclusion is wired (CPU skips warm-up, so 0 here; the
    # suppression behaviour itself is asserted in tests/obs).
    assert snap["warmup_excluded_dispatches"] == 0

    # INTELLILLM_PEAK_FLOPS turns MFU finite over the same recorded
    # steps (CPU runs can still produce a number for trend lines).
    monkeypatch.setenv("INTELLILLM_PEAK_FLOPS", "1e12")
    tracker.attach_device()
    mfu = tracker.record_step(1e-3)
    assert mfu is not None and math.isfinite(mfu) and mfu > 0
    assert tracker.snapshot()["mfu"] is not None

    # Both servers serve the full ledger at GET /debug/efficiency from
    # the process-global tracker the engine just populated.
    from intellillm_tpu.entrypoints import api_server as demo_server
    from intellillm_tpu.entrypoints.openai import api_server as \
        openai_server
    for build_app in (demo_server.build_app, openai_server.build_app):
        served = _serve_and_fetch(build_app)
        assert served["status"] == 200
        data = served["data"]
        assert data["tokens_total"]["prefill"]["real"] == \
            tok["prefill"]["real"]
        assert data["tokens_total"]["prefill"]["pad"] == \
            tok["prefill"]["pad"]
        assert data["fill_ratio_avg"]["decode"]["block_width"] is not None
        assert data["top_waste"]
        assert data["per_bucket"]
        assert data["mfu"] is not None  # env override above is live

    # /health/detail carries the compact block (no per-bucket list).
    served = _serve_and_fetch(demo_server.build_app, "/health/detail")
    eff = served["data"]["efficiency"]
    assert eff["tokens_total"]["prefill"]["real"] > 0
    assert "per_bucket" not in eff
    assert len(eff["top_waste"]) <= 4
