"""Golden bit-equality suites for the features folded into the mixed
token-budget dispatch (PR 12): prefix-cache hit/miss, prompt_logprobs
panels, and best_of/beam fan-out.

The fixtures in `golden_mixed_fixtures.json` were RECORDED against the
legacy homogeneous prefill path at commit 0e40190 (the last commit where
that path existed), with `enable_chunked_prefill` off. After the
unification every feature executes through the mixed `(token_budget,)`
dispatch, and these tests assert the outputs still match the recorded
legacy outputs token for token — both at the default token budget
(whole prompts land in one chunk) and at a tiny budget that forces
multi-step chunk splits mid-prompt.

Sampled token ids must match exactly: the per-row gumbel noise depends
only on (seed, num_samples bucket, vocab) — not on batch padding — so
the mixed rows draw the same noise the legacy prefill rows drew.
prompt_logprob VALUES are compared with a small tolerance (flash
full-prompt attention vs per-row paged attention differ in float
reduction order); the token ids and top-k membership stay exact.

Regenerate (only meaningful against a pre-unification checkout):
    INTELLILLM_REGEN_GOLDEN=1 python -m pytest \
        tests/engine/test_mixed_golden.py -q
"""
import json
import os
import pathlib

import pytest

from intellillm_tpu import LLM, SamplingParams

FIXTURES = pathlib.Path(__file__).parent / "golden_mixed_fixtures.json"
REGEN = os.environ.get("INTELLILLM_REGEN_GOLDEN") == "1"

PREFIX = ("you are a helpful assistant and the user would like to know "
          "about the city of paris in france where the")
PREFIX_QUERIES = [
    "capital is big",
    "river runs fast and the water is blue",
    "people make red wine",
]
PLP_PROMPTS = [
    "hello my name is",
    "the president of the united states is",
    "the cat runs fast and the dog",
]
SAMPLED_PROMPTS = [
    "hello my name is",
    "the capital of france is",
]


def _llm(model_dir, **kw):
    return LLM(model=model_dir, dtype="float32",
               num_device_blocks_override=128, max_model_len=128,
               max_num_seqs=8, max_paddings=512, swap_space=0.01, **kw)


def _budget_variants(model_dir):
    """Engine configs the suites run under: the default budget and a
    tiny budget that forces real chunk splits and decode+prefill mixed
    steps. When regenerating, only the legacy default-path engine is
    built."""
    if REGEN:
        return {"default": _llm(model_dir)}
    return {
        "default": _llm(model_dir),
        "split": _llm(model_dir, max_num_batched_tokens=8),
    }


def _prefix_pos(llm):
    return len(llm.llm_engine.tokenizer.encode(PREFIX))


def _token_ids(outs):
    return [[list(o.token_ids) for o in r.outputs] for r in outs]


def _serialize_plp(request_output):
    plp = request_output.outputs and request_output.prompt_logprobs
    if not plp:
        return None
    out = []
    for entry in plp:
        if entry is None:
            out.append(None)
        else:
            out.append(sorted([int(t), float(lp)]
                              for t, lp in entry.items()))
    return out


def _run_prefix(llm):
    prompts = [PREFIX + " " + q for q in PREFIX_QUERIES]
    params = SamplingParams(temperature=0.0, max_tokens=12)
    ppos = _prefix_pos(llm)
    miss = llm.generate(prompts, params, prefix_pos=ppos)
    hit = llm.generate(prompts, params, prefix_pos=ppos)
    return {"miss": _token_ids(miss), "hit": _token_ids(hit)}


def _run_plp(llm):
    params = SamplingParams(temperature=0.0, max_tokens=4,
                            prompt_logprobs=2, logprobs=2, ignore_eos=True)
    outs = llm.generate(PLP_PROMPTS, params)
    return {
        "ids": _token_ids(outs),
        "plp": [_serialize_plp(o) for o in outs],
    }


def _run_best_of(llm):
    params = SamplingParams(temperature=0.8, n=3, best_of=3,
                            max_tokens=8, ignore_eos=True)
    return {"ids": _token_ids(llm.generate(SAMPLED_PROMPTS, params))}


def _run_beam(llm):
    params = SamplingParams(use_beam_search=True, temperature=0.0,
                            n=2, best_of=4, max_tokens=8)
    return {"ids": _token_ids(llm.generate(SAMPLED_PROMPTS, params))}


SUITES = {
    "prefix": _run_prefix,
    "prompt_logprobs": _run_plp,
    "best_of": _run_best_of,
    "beam": _run_beam,
}


def test_regen_golden_fixtures(tiny_opt_dir):
    """Not a test in normal runs: rewrites the fixture file when
    INTELLILLM_REGEN_GOLDEN=1 (meaningful only on a checkout that still
    has the legacy prefill path)."""
    if not REGEN:
        pytest.skip("fixture regeneration disabled")
    llm = _budget_variants(tiny_opt_dir)["default"]
    data = {name: fn(llm) for name, fn in SUITES.items()}
    FIXTURES.write_text(json.dumps(data, indent=1, sort_keys=True))


@pytest.fixture(scope="module")
def golden():
    if not FIXTURES.exists():
        pytest.skip("golden fixtures not recorded")
    return json.loads(FIXTURES.read_text())


@pytest.mark.skipif(REGEN, reason="regenerating fixtures")
@pytest.mark.parametrize("budget", ["default", "split"])
class TestMixedGolden:

    @pytest.fixture(scope="class")
    def llms(self, tiny_opt_dir):
        return _budget_variants(tiny_opt_dir)

    def test_prefix_cache_hit_and_miss(self, llms, golden, budget):
        got = _run_prefix(llms[budget])
        assert got["miss"] == golden["prefix"]["miss"]
        assert got["hit"] == golden["prefix"]["hit"]
        pool = llms[budget].llm_engine.scheduler.prefix_pool
        assert any(p.computed for p in pool.prefixes.values())

    def test_prompt_logprobs_panels(self, llms, golden, budget):
        got = _run_plp(llms[budget])
        want = golden["prompt_logprobs"]
        assert got["ids"] == want["ids"]
        assert len(got["plp"]) == len(want["plp"])
        for got_req, want_req in zip(got["plp"], want["plp"]):
            assert (got_req is None) == (want_req is None)
            if got_req is None:
                continue
            assert len(got_req) == len(want_req)
            for got_entry, want_entry in zip(got_req, want_req):
                assert (got_entry is None) == (want_entry is None)
                if got_entry is None:
                    continue
                got_toks = sorted(t for t, _ in got_entry)
                want_toks = sorted(t for t, _ in want_entry)
                assert got_toks == want_toks
                got_lp = dict((t, lp) for t, lp in got_entry)
                for t, lp in want_entry:
                    assert abs(got_lp[t] - lp) < 1e-3, (
                        f"token {t}: {got_lp[t]} vs {lp}")

    def test_best_of_fan_out(self, llms, golden, budget):
        got = _run_best_of(llms[budget])
        assert got["ids"] == golden["best_of"]["ids"]

    def test_beam_search(self, llms, golden, budget):
        got = _run_beam(llms[budget])
        assert got["ids"] == golden["beam"]["ids"]
