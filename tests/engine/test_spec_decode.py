"""Engine-integrated speculative decoding (greedy draft/verify).

Role parity: reference `vllm/worker/spec_decode/multi_step_worker.py:22`
+ `layers/rejection_sampler.py:9` — wired end-to-end here (the reference
never integrated its scaffold). The invariant under test: the emitted
stream is EXACTLY the target model's greedy stream, regardless of how
good or bad the draft model is.
"""
import pytest
import torch

from intellillm_tpu import LLM, SamplingParams


@pytest.fixture(scope="module")
def draft_llama_dir(tmp_path_factory):
    """A second tiny llama sharing the word tokenizer but with DIFFERENT
    random weights (seed 7): a plausible-but-imperfect draft."""
    from tests.conftest import _build_word_tokenizer
    from transformers import LlamaConfig, LlamaForCausalLM

    d = str(tmp_path_factory.mktemp("tiny-llama-draft"))
    _, vocab_size = _build_word_tokenizer(d)
    torch.manual_seed(7)
    config = LlamaConfig(
        vocab_size=vocab_size, hidden_size=32, intermediate_size=64,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=128, tie_word_embeddings=False,
        pad_token_id=0, bos_token_id=1, eos_token_id=1,
        torch_dtype=torch.float32)
    model = LlamaForCausalLM(config)
    model.eval()
    model.save_pretrained(d, safe_serialization=True)
    return d


def _run(model_dir, requests, **llm_kwargs):
    llm = LLM(model=model_dir, dtype="float32",
              num_device_blocks_override=128, max_model_len=128,
              max_num_seqs=8, max_paddings=512, swap_space=0.01,
              **llm_kwargs)
    engine = llm.llm_engine
    for rid, prompt, params in requests:
        engine.add_request(rid, prompt, params)
    outs = llm._run_engine(use_tqdm=False)
    return ([(o.request_id,
              [(tuple(c.token_ids), c.finish_reason) for c in o.outputs])
             for o in outs], engine)


def test_spec_decode_matches_plain_greedy(tiny_llama_dir, draft_llama_dir,
                                          example_prompts):
    reqs = [(str(i), p, SamplingParams(temperature=0.0, max_tokens=24,
                                       ignore_eos=True))
            for i, p in enumerate(example_prompts)]
    ref, _ = _run(tiny_llama_dir, reqs)
    got, engine = _run(tiny_llama_dir, reqs,
                       speculative_model=draft_llama_dir,
                       num_speculative_tokens=4)
    assert got == ref
    # The speculative path actually ran (draft tokens were scored).
    assert engine.worker.num_draft_tokens > 0


def test_spec_decode_perfect_draft_accepts_everything(tiny_llama_dir,
                                                      example_prompts):
    """Draft == target: every draft token must be accepted (acceptance
    rate 1.0) and outputs still match plain greedy."""
    reqs = [(str(i), p, SamplingParams(temperature=0.0, max_tokens=16,
                                       ignore_eos=True))
            for i, p in enumerate(example_prompts[:2])]
    ref, _ = _run(tiny_llama_dir, reqs)
    got, engine = _run(tiny_llama_dir, reqs,
                       speculative_model=tiny_llama_dir,
                       num_speculative_tokens=4)
    assert got == ref
    assert engine.worker.acceptance_rate() == 1.0


def test_spec_decode_with_stops(tiny_llama_dir, draft_llama_dir,
                                example_prompts):
    """Stops / EOS / max_tokens trim speculative overshoot identically to
    the plain engine."""
    probe, _ = _run(tiny_llama_dir,
                    [("0", example_prompts[0],
                      SamplingParams(temperature=0.0, max_tokens=4))])
    params = [
        SamplingParams(temperature=0.0, max_tokens=24,
                       stop_token_ids=[probe[0][1][0][0][0]]),
        SamplingParams(temperature=0.0, max_tokens=7, ignore_eos=True),
        SamplingParams(temperature=0.0, max_tokens=24, ignore_eos=True),
    ]
    reqs = [(str(i), p, sp)
            for i, (p, sp) in enumerate(zip(example_prompts, params))]
    ref, _ = _run(tiny_llama_dir, reqs)
    got, _ = _run(tiny_llama_dir, reqs,
                  speculative_model=draft_llama_dir,
                  num_speculative_tokens=4)
    assert got == ref


def test_spec_decode_partial_eligibility_mixed_batch(tiny_llama_dir,
                                                     draft_llama_dir,
                                                     example_prompts):
    """A batch mixing a greedy (spec-eligible) request with a sampled
    (ineligible) one: the greedy row takes the draft+verify round while
    the sampled row rides the plain dispatch in the SAME step, and both
    streams are token-exact vs the plain engine. Seeded sampling streams
    are K-dependent per fused call; the ineligible row advances one
    token per pass under the spec engine, so the plain twin runs
    num_decode_steps=1 (the greedy stream is K-independent)."""
    params = [
        SamplingParams(temperature=0.0, max_tokens=12, ignore_eos=True),
        SamplingParams(temperature=0.8, top_p=0.9, max_tokens=12,
                       ignore_eos=True),
    ]
    reqs = [(str(i), p, sp)
            for i, (p, sp) in enumerate(zip(example_prompts, params))]
    ref, _ = _run(tiny_llama_dir, reqs, num_decode_steps=1)
    got, engine = _run(tiny_llama_dir, reqs,
                       speculative_model=draft_llama_dir,
                       num_speculative_tokens=4)
    assert got == ref
    # The eligible row actually speculated — this was a mixed round,
    # not a whole-batch fallback.
    assert engine.worker.num_draft_tokens > 0


def test_spec_decode_chunked_prefill_bit_identical(tiny_llama_dir,
                                                   draft_llama_dir,
                                                   example_prompts):
    """Spec + chunked prefill compose: a tiny token budget forces real
    chunk splits and mixed steps (prefill chunks mirrored into the draft
    KV pool while resident decodes speculate), and the emitted greedy
    streams are still bit-identical to the plain engine."""
    prompts = example_prompts + [
        " ".join(["the cat runs fast and the dog"] * 5)]  # 35 tokens
    reqs = [(str(i), p, SamplingParams(temperature=0.0, max_tokens=16,
                                       ignore_eos=True))
            for i, p in enumerate(prompts)]
    ref, _ = _run(tiny_llama_dir, reqs)

    from intellillm_tpu.core import scheduler as sched_mod
    seen = {"mixed": 0, "split": 0, "spec_mixed": 0}
    orig = sched_mod.Scheduler._chunked_pass

    def spy(self, now):
        out = orig(self, now)
        seen["mixed"] += 1
        if any(start > 0 for start, _, _ in out.chunked_prefills.values()):
            seen["split"] += 1
        if out.spec_plan and out.chunked_prefills:
            seen["spec_mixed"] += 1
        return out

    sched_mod.Scheduler._chunked_pass = spy
    try:
        got, engine = _run(tiny_llama_dir, reqs,
                           speculative_model=draft_llama_dir,
                           num_speculative_tokens=4,
                           max_num_batched_tokens=12)
    finally:
        sched_mod.Scheduler._chunked_pass = orig

    assert got == ref
    assert engine.worker.num_draft_tokens > 0
    assert seen["split"] > 0, (
        "budget was sized to split the long prompt but no chunk split "
        "happened — the scenario degenerated to whole-prompt prefill")
    assert seen["spec_mixed"] > 0, (
        "no step combined prefill chunks with a speculating decode row")


def test_spec_decode_vocab_mismatch_rejected(tiny_llama_dir,
                                             tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM

    d = str(tmp_path_factory.mktemp("tiny-llama-othervocab"))
    torch.manual_seed(3)
    model = LlamaForCausalLM(LlamaConfig(
        vocab_size=77, hidden_size=32, intermediate_size=64,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=128, tie_word_embeddings=False,
        torch_dtype=torch.float32))
    model.save_pretrained(d, safe_serialization=True)
    with pytest.raises(ValueError, match="vocab"):
        _run(tiny_llama_dir, [], speculative_model=d)


def test_spec_decode_rejects_explicit_pipeline(tiny_llama_dir,
                                               draft_llama_dir,
                                               monkeypatch):
    """INTELLILLM_PIPELINE=1 set explicitly alongside a draft model is a
    config error at EngineArgs.create_engine_configs (the engine cannot
    overlap fetches across the draft/verify round trip). The DEFAULT
    auto-pipelining must NOT trip this — spec engines silently run
    synchronous stepping (every other test in this file relies on it)."""
    monkeypatch.setenv("INTELLILLM_PIPELINE", "1")
    with pytest.raises(ValueError, match="pipelined"):
        _run(tiny_llama_dir, [], speculative_model=draft_llama_dir,
             num_speculative_tokens=2)


def test_spec_decode_k_band_validation(tiny_llama_dir, draft_llama_dir):
    """--spec-k-min/--spec-k-max must bracket the initial K and be a
    sane band."""
    with pytest.raises(ValueError, match="spec_k_min"):
        _run(tiny_llama_dir, [], speculative_model=draft_llama_dir,
             num_speculative_tokens=2, spec_k_min=3, spec_k_max=2)
    with pytest.raises(ValueError, match="initial K"):
        _run(tiny_llama_dir, [], speculative_model=draft_llama_dir,
             num_speculative_tokens=5, spec_k_min=1, spec_k_max=4)


def test_spec_decode_tp2(tiny_llama_dir, draft_llama_dir, example_prompts):
    """Speculative decoding under TP=2 on the virtual mesh: both models
    shard over the same mesh; outputs still match plain greedy."""
    reqs = [(str(i), p, SamplingParams(temperature=0.0, max_tokens=12,
                                       ignore_eos=True))
            for i, p in enumerate(example_prompts[:2])]
    ref, _ = _run(tiny_llama_dir, reqs)
    got, _ = _run(tiny_llama_dir, reqs, tensor_parallel_size=2,
                  speculative_model=draft_llama_dir,
                  num_speculative_tokens=4)
    assert got == ref
