"""Engine-integrated speculative decoding (greedy draft/verify).

Role parity: reference `vllm/worker/spec_decode/multi_step_worker.py:22`
+ `layers/rejection_sampler.py:9` — wired end-to-end here (the reference
never integrated its scaffold). The invariant under test: the emitted
stream is EXACTLY the target model's greedy stream, regardless of how
good or bad the draft model is.
"""
import pytest
import torch

from intellillm_tpu import LLM, SamplingParams


@pytest.fixture(scope="module")
def draft_llama_dir(tmp_path_factory):
    """A second tiny llama sharing the word tokenizer but with DIFFERENT
    random weights (seed 7): a plausible-but-imperfect draft."""
    from tests.conftest import _build_word_tokenizer
    from transformers import LlamaConfig, LlamaForCausalLM

    d = str(tmp_path_factory.mktemp("tiny-llama-draft"))
    _, vocab_size = _build_word_tokenizer(d)
    torch.manual_seed(7)
    config = LlamaConfig(
        vocab_size=vocab_size, hidden_size=32, intermediate_size=64,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=128, tie_word_embeddings=False,
        pad_token_id=0, bos_token_id=1, eos_token_id=1,
        torch_dtype=torch.float32)
    model = LlamaForCausalLM(config)
    model.eval()
    model.save_pretrained(d, safe_serialization=True)
    return d


def _run(model_dir, requests, **llm_kwargs):
    llm = LLM(model=model_dir, dtype="float32",
              num_device_blocks_override=128, max_model_len=128,
              max_num_seqs=8, max_paddings=512, swap_space=0.01,
              **llm_kwargs)
    engine = llm.llm_engine
    for rid, prompt, params in requests:
        engine.add_request(rid, prompt, params)
    outs = llm._run_engine(use_tqdm=False)
    return ([(o.request_id,
              [(tuple(c.token_ids), c.finish_reason) for c in o.outputs])
             for o in outs], engine)


def test_spec_decode_matches_plain_greedy(tiny_llama_dir, draft_llama_dir,
                                          example_prompts):
    reqs = [(str(i), p, SamplingParams(temperature=0.0, max_tokens=24,
                                       ignore_eos=True))
            for i, p in enumerate(example_prompts)]
    ref, _ = _run(tiny_llama_dir, reqs)
    got, engine = _run(tiny_llama_dir, reqs,
                       speculative_model=draft_llama_dir,
                       num_speculative_tokens=4)
    assert got == ref
    # The speculative path actually ran (draft tokens were scored).
    assert engine.worker.num_draft_tokens > 0


def test_spec_decode_perfect_draft_accepts_everything(tiny_llama_dir,
                                                      example_prompts):
    """Draft == target: every draft token must be accepted (acceptance
    rate 1.0) and outputs still match plain greedy."""
    reqs = [(str(i), p, SamplingParams(temperature=0.0, max_tokens=16,
                                       ignore_eos=True))
            for i, p in enumerate(example_prompts[:2])]
    ref, _ = _run(tiny_llama_dir, reqs)
    got, engine = _run(tiny_llama_dir, reqs,
                       speculative_model=tiny_llama_dir,
                       num_speculative_tokens=4)
    assert got == ref
    assert engine.worker.acceptance_rate() == 1.0


def test_spec_decode_with_stops(tiny_llama_dir, draft_llama_dir,
                                example_prompts):
    """Stops / EOS / max_tokens trim speculative overshoot identically to
    the plain engine."""
    probe, _ = _run(tiny_llama_dir,
                    [("0", example_prompts[0],
                      SamplingParams(temperature=0.0, max_tokens=4))])
    params = [
        SamplingParams(temperature=0.0, max_tokens=24,
                       stop_token_ids=[probe[0][1][0][0][0]]),
        SamplingParams(temperature=0.0, max_tokens=7, ignore_eos=True),
        SamplingParams(temperature=0.0, max_tokens=24, ignore_eos=True),
    ]
    reqs = [(str(i), p, sp)
            for i, (p, sp) in enumerate(zip(example_prompts, params))]
    ref, _ = _run(tiny_llama_dir, reqs)
    got, _ = _run(tiny_llama_dir, reqs,
                  speculative_model=draft_llama_dir,
                  num_speculative_tokens=4)
    assert got == ref


def test_spec_decode_mixed_batch_falls_back(tiny_llama_dir,
                                            draft_llama_dir,
                                            example_prompts):
    """A batch containing a sampled request is ineligible for the
    speculative path; the fallback still produces the exact same outputs
    as the plain engine (seeded sampling included)."""
    params = [
        SamplingParams(temperature=0.0, max_tokens=12, ignore_eos=True),
        SamplingParams(temperature=0.8, top_p=0.9, max_tokens=12,
                       ignore_eos=True),
    ]
    reqs = [(str(i), p, sp)
            for i, (p, sp) in enumerate(zip(example_prompts, params))]
    # Seeded sampling streams are K-dependent (per-fused-call seed base =
    # hash(output_len)); speculative mode forces K = num_spec_tokens + 1,
    # so the plain twin must run the same K for token-exact comparison.
    ref, _ = _run(tiny_llama_dir, reqs, num_decode_steps=5)
    got, _ = _run(tiny_llama_dir, reqs,
                  speculative_model=draft_llama_dir,
                  num_speculative_tokens=4)
    assert got == ref


def test_spec_decode_vocab_mismatch_rejected(tiny_llama_dir,
                                             tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM

    d = str(tmp_path_factory.mktemp("tiny-llama-othervocab"))
    torch.manual_seed(3)
    model = LlamaForCausalLM(LlamaConfig(
        vocab_size=77, hidden_size=32, intermediate_size=64,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=128, tie_word_embeddings=False,
        torch_dtype=torch.float32))
    model.save_pretrained(d, safe_serialization=True)
    with pytest.raises(ValueError, match="vocab"):
        _run(tiny_llama_dir, [], speculative_model=d)


def test_spec_decode_tp2(tiny_llama_dir, draft_llama_dir, example_prompts):
    """Speculative decoding under TP=2 on the virtual mesh: both models
    shard over the same mesh; outputs still match plain greedy."""
    reqs = [(str(i), p, SamplingParams(temperature=0.0, max_tokens=12,
                                       ignore_eos=True))
            for i, p in enumerate(example_prompts[:2])]
    ref, _ = _run(tiny_llama_dir, reqs)
    got, _ = _run(tiny_llama_dir, reqs, tensor_parallel_size=2,
                  speculative_model=draft_llama_dir,
                  num_speculative_tokens=4)
    assert got == ref
