"""End-to-end observability: a real CPU engine run must export per-phase
timing that adds up, count XLA compiles exactly once per jit bucket, and
leave an ordered flight-recorder trace per request.

These are the PR's acceptance tests — they drive the full stack
(LLM → LLMEngine → Scheduler → Worker → ModelRunner) rather than the
obs primitives in isolation (tests/obs/ covers those).
"""
import pytest

from intellillm_tpu import LLM, SamplingParams
from intellillm_tpu.engine.metrics import _Metrics, _PROMETHEUS
from intellillm_tpu.obs import (get_compile_tracker, get_flight_recorder,
                                get_slo_tracker, get_step_tracer)


@pytest.fixture
def fresh_obs():
    """Reset the process-global observability state around the test so
    earlier engine tests in the same process don't pollute counters."""
    get_step_tracer().reset_for_testing()
    get_compile_tracker().reset_for_testing()
    get_flight_recorder().reset_for_testing()
    get_slo_tracker().reset_for_testing()
    _Metrics.reset_for_testing()
    yield
    get_slo_tracker().reset_for_testing()
    _Metrics.reset_for_testing()


def _registry_value(name: str, label_filter=None) -> float:
    from prometheus_client import REGISTRY
    total = 0.0
    for metric in REGISTRY.collect():
        for sample in metric.samples:
            if sample.name == name and (
                    label_filter is None or
                    all(sample.labels.get(k) == v
                        for k, v in label_filter.items())):
                total += sample.value
    return total


@pytest.mark.skipif(not _PROMETHEUS, reason="needs prometheus_client")
def test_engine_run_exports_phase_breakdown(tiny_opt_dir, fresh_obs):
    llm = LLM(model=tiny_opt_dir, dtype="float32",
              num_device_blocks_override=128, max_model_len=128,
              max_num_seqs=8, max_paddings=512, swap_space=0.01,
              disable_log_stats=False)
    engine = llm.llm_engine
    params = SamplingParams(temperature=0.0, max_tokens=24, ignore_eos=True)
    for i, prompt in enumerate(["hello my name is",
                                "the capital of france is"]):
        engine.add_request(str(i), prompt, params)
    outs = llm._run_engine(use_tqdm=False)
    assert all(len(o.outputs[0].token_ids) == 24 for o in outs)

    phase_sum = _registry_value("intellillm_step_phase_seconds_sum")
    step_sum = _registry_value("intellillm_step_time_seconds_sum")
    n_steps = _registry_value("intellillm_step_time_seconds_count")
    assert n_steps > 0, "no step histogram samples exported"
    assert phase_sum > 0.0
    # Exclusive phase accounting: the sum must cover at least 80% of step
    # wall time (acceptance criterion) and can never exceed it by more
    # than drain jitter.
    assert phase_sum >= 0.8 * step_sum, (
        f"phases cover only {phase_sum / step_sum:.0%} of step time")
    assert phase_sum <= step_sum * 1.05 + 0.005

    # The hot phases must all have fired on a prefill+decode run.
    for phase in ("schedule", "prepare_inputs", "execute", "sample",
                  "detokenize"):
        assert _registry_value("intellillm_step_phase_seconds_count",
                               {"phase": phase}) > 0, f"{phase} missing"

    # The engine also keeps the last drained breakdown in-process. (The
    # last pipelined drain can be a tail finalize with no execute span,
    # so only non-emptiness is guaranteed.)
    assert engine.last_step_time > 0.0
    assert engine.last_step_phases


def test_compile_counters_once_per_bucket(tiny_opt_dir, fresh_obs):
    llm = LLM(model=tiny_opt_dir, dtype="float32",
              num_device_blocks_override=128, max_model_len=128,
              max_num_seqs=8, max_paddings=512, swap_space=0.01)
    engine = llm.llm_engine
    params = SamplingParams(temperature=0.0, max_tokens=16, ignore_eos=True)

    engine.add_request("11", "hello my name is", params)
    llm._run_engine(use_tqdm=False)
    snap1 = get_compile_tracker().snapshot()
    # Prompts execute as chunk rows of the mixed program — there is no
    # separate "prefill" executable anymore.
    assert snap1["compiles"].get("mixed") == 1, snap1
    assert "prefill" not in snap1["compiles"], snap1
    decode_compiles1 = sum(v for k, v in snap1["compiles"].items()
                           if k.startswith("decode"))
    assert decode_compiles1 >= 1, snap1
    assert snap1["live_executables"] == sum(snap1["compiles"].values())

    # Identical second request: every bucket is warm — zero new compiles,
    # only cache hits.
    engine.add_request("12", "hello my name is", params)
    llm._run_engine(use_tqdm=False)
    snap2 = get_compile_tracker().snapshot()
    assert snap2["compiles"] == snap1["compiles"], (
        f"cache hit recompiled: {snap1['compiles']} -> {snap2['compiles']}")
    assert sum(snap2["cache_hits"].values()) > sum(
        snap1["cache_hits"].values())


def test_flight_recorder_traces_request_lifecycle(tiny_opt_dir, fresh_obs):
    llm = LLM(model=tiny_opt_dir, dtype="float32",
              num_device_blocks_override=128, max_model_len=128,
              max_num_seqs=8, max_paddings=512, swap_space=0.01)
    engine = llm.llm_engine
    params = SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)
    engine.add_request("21", "the cat runs fast and the dog", params)
    llm._run_engine(use_tqdm=False)

    trace = get_flight_recorder().get_trace("21")
    assert trace is not None
    events = [e["event"] for e in trace]
    # Ordered lifecycle: arrival → admission → scheduling → prefill →
    # first token → finish, with monotonically nondecreasing timestamps.
    for a, b in [("arrived", "queued"), ("queued", "scheduled"),
                 ("scheduled", "prefill_start"),
                 ("prefill_start", "first_token"),
                 ("first_token", "finished")]:
        assert events.index(a) < events.index(b), events
    assert all(trace[i]["ts"] <= trace[i + 1]["ts"]
               for i in range(len(trace) - 1))
    assert trace[events.index("finished")].get("detail") == "length"
    # Finished: moved off the live table into the finished ring.
    assert "21" not in get_flight_recorder().live_request_ids()
    assert any(x["request_id"] == "21"
               for x in get_flight_recorder().recent_finished())

    # The finish fed the SLO tracker exactly once, with metrics derived
    # from this trace.
    s = get_slo_tracker().summary()
    assert s["window"] == 1
    assert s["finished_total"] == {"length": 1}
    assert s["ttft_ms"]["p50"] > 0.0
    assert s["tpot_ms"]["p50"] >= 0.0
