"""logits_processors: host escape path.

Reference parity: `vllm/sampling_params.py` LogitsProcessor +
`vllm/model_executor/layers/sampler.py:_apply_logits_processors`.
Processor-bearing rows get raw logits fetched from the device and are
re-sampled on host (scheduler forces K=1); other rows in the same batch
stay on the pure device path.
"""
import numpy as np
import pytest

from intellillm_tpu import LLM, SamplingParams


def _run(model_dir, prompts, params_list, num_decode_steps=1):
    llm = LLM(model=model_dir, dtype="float32",
              num_device_blocks_override=128, max_model_len=128,
              max_num_seqs=8, max_paddings=512, swap_space=0.01,
              num_decode_steps=num_decode_steps)
    engine = llm.llm_engine
    for i, (prompt, params) in enumerate(zip(prompts, params_list)):
        engine.add_request(str(i), prompt, params)
    outs = llm._run_engine(use_tqdm=False)
    return [list(o.outputs[0].token_ids) for o in outs]


def test_identity_processor_matches_plain(tiny_opt_dir, example_prompts):
    plain = _run(tiny_opt_dir, example_prompts[:2],
                 [SamplingParams(temperature=0.0, max_tokens=8)] * 2)
    ident = _run(tiny_opt_dir, example_prompts[:2],
                 [SamplingParams(temperature=0.0, max_tokens=8,
                                 logits_processors=[lambda out, l: l])] * 2)
    assert ident == plain


def test_ban_token_processor(tiny_opt_dir, example_prompts):
    plain = _run(tiny_opt_dir, example_prompts[:1],
                 [SamplingParams(temperature=0.0, max_tokens=8)])
    banned = plain[0][0]   # greedy favorite incl. the very first token

    def ban(out_ids, logits):
        logits[banned] = -np.inf
        return logits

    got = _run(tiny_opt_dir, example_prompts[:1],
               [SamplingParams(temperature=0.0, max_tokens=8,
                               logits_processors=[ban])])
    assert banned not in got[0]
    assert got[0] != plain[0]


def test_force_token_sequence(tiny_llama_dir, example_prompts):
    """Forcing processor fully determines the output, including the very
    first (prefill-sampled) token."""
    forced = [7, 11, 13, 17, 19, 23]

    def force(out_ids, logits):
        t = forced[len(out_ids)]
        logits[:] = -np.inf
        logits[t] = 0.0
        return logits

    got = _run(tiny_llama_dir, example_prompts[:1],
               [SamplingParams(temperature=0.0,
                               max_tokens=len(forced),
                               logits_processors=[force])])
    assert got[0] == forced


def test_mixed_batch_with_fused_decode(tiny_opt_dir, example_prompts):
    """Processor rows coexist with plain rows in one batch (engine
    configured for fused K=8: the scheduler must force K=1); plain rows
    match their processor-free solo run."""
    plain_solo = _run(tiny_opt_dir, example_prompts[1:3],
                      [SamplingParams(temperature=0.0, max_tokens=8)] * 2,
                      num_decode_steps=8)

    def ban0(out_ids, logits):
        logits[4] = -np.inf
        return logits

    params = [SamplingParams(temperature=0.0, max_tokens=8,
                             logits_processors=[ban0]),
              SamplingParams(temperature=0.0, max_tokens=8),
              SamplingParams(temperature=0.0, max_tokens=8)]
    got = _run(tiny_opt_dir, example_prompts[:3], params,
               num_decode_steps=8)
    assert got[1:] == plain_solo
    assert 4 not in got[0]


def test_processor_with_random_sampling_is_deterministic(
        tiny_opt_dir, example_prompts):
    """Host Gumbel sampling is seeded per (engine seed, seq, step): two
    identical runs agree, and the ban is respected under temperature."""
    def ban(out_ids, logits):
        logits[5] = -np.inf
        return logits

    params = [SamplingParams(temperature=0.8, top_p=0.9, max_tokens=8,
                             logits_processors=[ban])]
    a = _run(tiny_opt_dir, example_prompts[:1], params)
    b = _run(tiny_opt_dir, example_prompts[:1], params)
    assert a == b
    assert 5 not in a[0]


def test_non_callable_processor_rejected(tiny_opt_dir):
    llm = LLM(model=tiny_opt_dir, dtype="float32",
              num_device_blocks_override=64, max_model_len=64,
              max_num_seqs=2, max_paddings=256, swap_space=0.01)
    with pytest.raises(ValueError):
        llm.llm_engine.add_request(
            "x", "hello", SamplingParams(logits_processors=["nope"]))
