"""Rejection-sampler distribution tests.

Reference: `tests/samplers/test_rejection_sampler.py` (distribution-level
property tests). The key property (Leviathan et al.): the marginal of the
emitted token at each position equals the target distribution p,
regardless of the draft q; the expected acceptance rate per position is
sum_x min(p(x), q(x)).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from intellillm_tpu.layers.rejection_sampler import (RejectionSampler,
                                                     rejection_sample)


def _rand_dist(rng, shape):
    logits = rng.standard_normal(shape) * 1.5
    e = np.exp(logits - logits.max(-1, keepdims=True))
    return (e / e.sum(-1, keepdims=True)).astype(np.float32)


def _run_many(target, draft, draft_ids_sampler, n_trials, k, v, seed=0):
    """Vectorize trials through the batch dimension."""
    rng = np.random.default_rng(seed)
    tp = jnp.asarray(np.broadcast_to(target, (n_trials, k, v)))
    # Draft tokens sampled fresh from q per trial.
    draft_ids = np.stack(
        [draft_ids_sampler(rng) for _ in range(n_trials)])      # [N, K]
    dp = jnp.asarray(np.broadcast_to(draft, (n_trials, k, v)))
    bonus = jnp.asarray(
        rng.integers(0, v, size=n_trials).astype(np.int32))
    out, num_accepted = jax.jit(rejection_sample)(
        jax.random.PRNGKey(seed), tp, dp,
        jnp.asarray(draft_ids.astype(np.int32)), bonus)
    return np.asarray(out), np.asarray(num_accepted)


@pytest.mark.parametrize("seed", [0, 1])
def test_first_position_marginal_matches_target(seed):
    """Empirical distribution of the first emitted token ≈ p[0]."""
    rng = np.random.default_rng(seed)
    k, v, n = 3, 8, 60000
    target = _rand_dist(rng, (k, v))
    draft = _rand_dist(rng, (k, v))

    def sample_draft(r):
        return np.array([r.choice(v, p=draft[t]) for t in range(k)])

    out, _ = _run_many(target, draft, sample_draft, n, k, v, seed)
    first = out[:, 0]
    assert (first >= 0).all()
    emp = np.bincount(first, minlength=v) / n
    np.testing.assert_allclose(emp, target[0], atol=0.015)


def test_acceptance_rate_matches_theory():
    rng = np.random.default_rng(3)
    k, v, n = 1, 16, 60000
    target = _rand_dist(rng, (k, v))
    draft = _rand_dist(rng, (k, v))

    def sample_draft(r):
        return np.array([r.choice(v, p=draft[0])])

    _, num_accepted = _run_many(target, draft, sample_draft, n, k, v)
    expected = np.minimum(target[0], draft[0]).sum()
    assert abs(num_accepted.mean() - expected) < 0.01


def test_identical_distributions_accept_everything():
    rng = np.random.default_rng(5)
    k, v, n = 4, 8, 2000
    target = _rand_dist(rng, (k, v))

    def sample_draft(r):
        return np.array([r.choice(v, p=target[t]) for t in range(k)])

    out, num_accepted = _run_many(target, target, sample_draft, n, k, v)
    assert (num_accepted == k).all()
    # Bonus token present at position k, no -1 anywhere.
    assert (out >= 0).all()


def test_disjoint_support_rejects_and_recovers_target():
    """Draft mass entirely where p = 0 → always reject at position 0 and
    the replacement is drawn exactly from p."""
    k, v, n = 2, 8, 60000
    target = np.zeros((k, v), np.float32)
    target[:, :4] = 0.25
    draft = np.zeros((k, v), np.float32)
    draft[:, 4:] = 0.25

    def sample_draft(r):
        return r.integers(4, 8, size=k)

    out, num_accepted = _run_many(target, draft, sample_draft, n, k, v)
    assert (num_accepted == 0).all()
    assert (out[:, 1:] == -1).all()
    emp = np.bincount(out[:, 0], minlength=v) / n
    np.testing.assert_allclose(emp, target[0], atol=0.015)


def test_sampler_wrapper_metrics():
    rng = np.random.default_rng(7)
    b, k, v = 32, 4, 8
    sampler = RejectionSampler()
    tp = jnp.asarray(_rand_dist(rng, (b, k, v)))
    dp = jnp.asarray(_rand_dist(rng, (b, k, v)))
    ids = jnp.asarray(rng.integers(0, v, size=(b, k)).astype(np.int32))
    bonus = jnp.asarray(rng.integers(0, v, size=b).astype(np.int32))
    out, num_accepted = sampler(jax.random.PRNGKey(0), tp, dp, ids, bonus)
    assert sampler.num_draft_tokens == b * k
    assert 0.0 <= sampler.acceptance_rate <= 1.0
    assert sampler.num_emitted_tokens == int((num_accepted + 1).sum())
