"""Sampler unit tests with synthetic logits (reference pattern:
`tests/samplers/test_sampler.py` MockLogitsSampler)."""
import jax.numpy as jnp
import numpy as np
import pytest

from intellillm_tpu.layers.sampler import (SamplingTensors, apply_penalties,
                                           sample)
from intellillm_tpu.sampling_params import SamplingParams


def run_sample(logits, temps, top_ks=None, top_ps=None, min_ps=None,
               seeds=None, **kw):
    n, v = logits.shape
    temps = jnp.asarray(temps, jnp.float32)
    top_ks = jnp.asarray(top_ks if top_ks is not None else [v] * n, jnp.int32)
    top_ps = jnp.asarray(top_ps if top_ps is not None else [1.0] * n,
                         jnp.float32)
    min_ps = jnp.asarray(min_ps if min_ps is not None else [0.0] * n,
                         jnp.float32)
    seeds = jnp.asarray(seeds if seeds is not None else np.arange(n),
                        jnp.uint32)
    return sample(jnp.asarray(logits), temps, top_ks, top_ps, min_ps, seeds,
                  logprob_k=8, **kw)


def test_greedy_picks_argmax():
    logits = np.random.default_rng(0).normal(size=(4, 50)).astype(np.float32)
    sampled, lp, tk_ids, tk_lp = run_sample(logits, temps=[0.0] * 4)
    np.testing.assert_array_equal(np.asarray(sampled)[:, 0],
                                  logits.argmax(-1))
    # Sampled logprob matches log-softmax of argmax.
    ref = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
    np.testing.assert_allclose(np.asarray(lp)[:, 0],
                               ref[np.arange(4), logits.argmax(-1)],
                               rtol=1e-4)


def test_topk_restricts_support():
    rng = np.random.default_rng(1)
    logits = rng.normal(size=(1, 100)).astype(np.float32)
    top3 = set(np.argsort(logits[0])[-3:].tolist())
    for seed in range(20):
        sampled, *_ = run_sample(logits, temps=[1.0], top_ks=[3],
                                 seeds=[seed], do_topk=True)
        assert int(np.asarray(sampled)[0, 0]) in top3


def test_topp_keeps_argmax():
    logits = np.zeros((1, 10), np.float32)
    logits[0, 7] = 10.0  # prob ~1
    for seed in range(10):
        sampled, *_ = run_sample(logits, temps=[1.0], top_ps=[0.1],
                                 seeds=[seed], do_topp=True)
        assert int(np.asarray(sampled)[0, 0]) == 7


def test_seeded_sampling_deterministic():
    logits = np.random.default_rng(2).normal(size=(2, 64)).astype(np.float32)
    a = run_sample(logits, temps=[0.8, 0.8], seeds=[42, 43])[0]
    b = run_sample(logits, temps=[0.8, 0.8], seeds=[42, 43])[0]
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    c = run_sample(logits, temps=[0.8, 0.8], seeds=[44, 45])[0]
    assert not (np.asarray(a) == np.asarray(c)).all()


def test_multi_sample_distinct_seeds():
    logits = np.random.default_rng(3).normal(size=(1, 64)).astype(np.float32)
    sampled, *_ = run_sample(logits, temps=[1.5], seeds=[7], num_samples=8)
    vals = np.asarray(sampled)[0]
    assert len(set(vals.tolist())) > 1, "independent samples expected"


def test_topk_logprob_panel_sorted():
    logits = np.random.default_rng(4).normal(size=(2, 30)).astype(np.float32)
    _, _, tk_ids, tk_lp = run_sample(logits, temps=[0.0, 0.0])
    lp = np.asarray(tk_lp)
    assert (np.diff(lp, axis=-1) <= 1e-6).all(), "panel must be descending"
    ref = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
    np.testing.assert_allclose(lp[:, 0], ref.max(-1), rtol=1e-4)


def test_penalties():
    logits = jnp.zeros((1, 8), jnp.float32)
    prompt_mask = np.zeros((1, 8), bool)
    prompt_mask[0, 1] = True
    counts = np.zeros((1, 8), np.int32)
    counts[0, 2] = 3
    out = apply_penalties(
        logits, jnp.asarray(prompt_mask), jnp.asarray(counts),
        presence_penalties=jnp.asarray([0.5], jnp.float32),
        frequency_penalties=jnp.asarray([0.1], jnp.float32),
        repetition_penalties=jnp.asarray([2.0], jnp.float32))
    out = np.asarray(out)
    assert out[0, 1] == 0.0  # rep penalty on 0 logit stays 0 (scaling)
    np.testing.assert_allclose(out[0, 2], -0.1 * 3 - 0.5)  # freq + presence
    assert out[0, 0] == 0.0  # untouched


def test_sampling_tensors_build_flags():
    sp_greedy = SamplingParams(temperature=0.0, max_tokens=4)
    sp_topk = SamplingParams(temperature=0.9, top_k=5, max_tokens=4)
    st = SamplingTensors.build([sp_greedy, sp_topk], [1, 2],
                               [([1], []), ([2], [])], vocab_size=100,
                               padded_n=4)
    assert st.do_topk and not st.do_penalties
    assert st.temperatures[1] == np.float32(0.9)
    assert st.top_ks[0] == 100  # disabled → vocab
    assert st.top_ks[2] == 100  # padding rows


def test_penalty_tensors_from_tokens_matches_host_scatter():
    """Device-side [N,V] scatter == the old host construction."""
    import jax.numpy as jnp
    import numpy as np
    from intellillm_tpu.layers.sampler import penalty_tensors_from_tokens

    vocab = 12
    rows = [([1, 3, 3, 5], [2, 2, 7]), ([0, 11], []), ([4], [4, 4, 4])]
    lp = max(len(p) for p, _ in rows)
    lo = max(len(o) for _, o in rows)
    pt = np.full((4, lp), vocab, np.int32)     # padded row 3 = all-pad
    ot = np.full((4, lo), vocab, np.int32)
    for i, (p, o) in enumerate(rows):
        pt[i, :len(p)] = p
        ot[i, :len(o)] = o
    pm, oc = penalty_tensors_from_tokens(jnp.asarray(pt), jnp.asarray(ot),
                                         vocab)
    pm, oc = np.asarray(pm), np.asarray(oc)
    ref_pm = np.zeros((4, vocab), bool)
    ref_oc = np.zeros((4, vocab), np.int32)
    for i, (p, o) in enumerate(rows):
        ref_pm[i, p] = True
        np.add.at(ref_oc[i], o, 1)
    np.testing.assert_array_equal(pm, ref_pm)
    np.testing.assert_array_equal(oc, ref_oc)


def test_prompt_logprobs_match_hf(tiny_opt_dir):
    """prompt_logprobs golden vs HF transformers per-token log-softmax
    (reference format: entry 0 is None; entry t maps token t (and the
    top-k panel) to log P(token_t | tokens_<t))."""
    import numpy as np
    import torch
    from intellillm_tpu import LLM, SamplingParams

    prompt = "the capital of france is the capital of france"
    llm = LLM(model=tiny_opt_dir, dtype="float32",
              num_device_blocks_override=128, max_model_len=128,
              max_num_seqs=8, swap_space=0.01)
    out = llm.generate([prompt],
                       SamplingParams(temperature=0.0, max_tokens=1,
                                      prompt_logprobs=3))[0]
    plp = out.prompt_logprobs
    token_ids = out.prompt_token_ids
    n = len(token_ids)
    assert plp is not None and len(plp) == n
    assert plp[0] is None

    from transformers import AutoModelForCausalLM
    model = AutoModelForCausalLM.from_pretrained(tiny_opt_dir,
                                                 torch_dtype=torch.float32)
    with torch.no_grad():
        logits = model(torch.tensor([token_ids])).logits[0]
    ref_lp = torch.log_softmax(logits.float(), dim=-1).numpy()

    for t in range(1, n):
        d = plp[t]
        assert token_ids[t] in d
        np.testing.assert_allclose(d[token_ids[t]],
                                   ref_lp[t - 1, token_ids[t]],
                                   rtol=2e-3, atol=2e-3)
        # Top-k panel entries also match HF.
        for tok, lp in d.items():
            np.testing.assert_allclose(lp, ref_lp[t - 1, tok], rtol=2e-3,
                                       atol=2e-3)
        assert len(d) >= 3


def test_prompt_logprobs_mixed_batch(tiny_opt_dir, example_prompts):
    """A batch mixing prompt_logprobs and plain requests: only the
    requesting ones get the list; generations are unaffected."""
    from intellillm_tpu import LLM, SamplingParams

    llm = LLM(model=tiny_opt_dir, dtype="float32",
              num_device_blocks_override=128, max_model_len=128,
              max_num_seqs=8, swap_space=0.01)
    plain = llm.generate(example_prompts[:2],
                         SamplingParams(temperature=0.0, max_tokens=6))
    engine = llm.llm_engine
    engine.add_request("0", example_prompts[0],
                       SamplingParams(temperature=0.0, max_tokens=6,
                                      prompt_logprobs=2))
    engine.add_request("1", example_prompts[1],
                       SamplingParams(temperature=0.0, max_tokens=6))
    outs = {o.request_id: o for o in llm._run_engine(use_tqdm=False)}
    assert outs["0"].prompt_logprobs is not None
    assert outs["1"].prompt_logprobs is None
    assert outs["0"].outputs[0].token_ids == plain[0].outputs[0].token_ids
    assert outs["1"].outputs[0].token_ids == plain[1].outputs[0].token_ids
