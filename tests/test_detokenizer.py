"""Incremental detokenization must equal one-shot decoding."""
import pytest

from intellillm_tpu.transformers_utils.detokenizer import (
    detokenize_incrementally)


@pytest.fixture(scope="module")
def tokenizer(tmp_path_factory):
    from tests.conftest import _build_word_tokenizer
    d = str(tmp_path_factory.mktemp("tok"))
    tok, _ = _build_word_tokenizer(d)
    return tok


def test_incremental_equals_full_decode(tokenizer):
    text = "the cat runs fast and the dog is slow"
    ids = tokenizer.encode(text)
    prompt_ids, gen_ids = ids[:3], ids[3:]

    tokens = None
    prefix_offset = read_offset = 0
    out_text = ""
    all_ids = list(prompt_ids)
    for tid in gen_ids:
        all_ids.append(tid)
        new_tokens, new_text, prefix_offset, read_offset = \
            detokenize_incrementally(tokenizer, all_ids, tokens,
                                     prefix_offset, read_offset,
                                     skip_special_tokens=True)
        if tokens is None:
            tokens = new_tokens
        else:
            tokens.extend(new_tokens)
        out_text += new_text

    full = tokenizer.decode(gen_ids, skip_special_tokens=True)
    assert out_text.strip() == full.strip()


def test_first_token_not_dropped(tokenizer):
    # Regression: the first generated token's text must appear.
    ids = tokenizer.encode("hello name")
    prompt_ids, first_gen = ids[:1], ids[1]
    all_ids = prompt_ids + [first_gen]
    _, new_text, _, _ = detokenize_incrementally(
        tokenizer, all_ids, None, 0, 0, skip_special_tokens=True)
    assert "name" in new_text
