"""Stability tests for the shared affinity-key helper.

The router and the prefix pool key on the SAME function; these tests pin
the exact key values so any change to the hashing scheme (which would
silently break cross-process routing affinity and invalidate persisted
routing state) fails loudly.
"""
import subprocess
import sys

from intellillm_tpu.affinity import (affinity_key, prompt_affinity_key,
                                     stable_hash, truncate_to_block)
from intellillm_tpu.prefix import Prefix, PrefixPool

# Pinned constants: blake2b(digest_size=8) over little-endian int64
# lora_int_id followed by the packed int64 token ids. These must NEVER
# change across releases — routers and pools in different processes (and
# different versions) must agree on them.
PINNED = {
    ((1, 2, 3, 4), 0): 2821693476514209883,
    ((1, 2, 3, 4), 7): 1824364471692216556,
    ((), 0): 1786884285633530058,
    (tuple(range(32)), 0): 10393153729583416920,
}


def test_pinned_key_values():
    for (token_ids, lora), expected in PINNED.items():
        assert affinity_key(token_ids, lora) == expected


def test_lora_id_separates_keys():
    ids = (5, 6, 7, 8)
    assert affinity_key(ids, 0) != affinity_key(ids, 1)


def test_key_is_order_sensitive():
    assert affinity_key((1, 2, 3, 4)) != affinity_key((4, 3, 2, 1))


def test_key_stable_across_processes():
    # The whole point vs builtin hash(): immune to PYTHONHASHSEED.
    code = ("from intellillm_tpu.affinity import affinity_key;"
            "print(affinity_key((1, 2, 3, 4), 0))")
    for seed in ("0", "12345"):
        out = subprocess.run(
            [sys.executable, "-c", code],
            env={"PYTHONHASHSEED": seed, "PATH": "/usr/bin:/bin",
                 "PYTHONPATH": "/root/repo"},
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
        assert int(out.stdout.strip()) == PINNED[((1, 2, 3, 4), 0)]


def test_truncate_to_block():
    assert truncate_to_block(list(range(10)), 4) == tuple(range(8))
    assert truncate_to_block([1, 2, 3], 4) == ()
    assert truncate_to_block(list(range(8)), 4) == tuple(range(8))


def test_prompt_affinity_key_caps_at_max_blocks():
    # 40 tokens truncate to 2 blocks (32 tokens), below the 4-block cap,
    # so the key equals the plain 32-token key...
    k40 = prompt_affinity_key(list(range(40)), block_size=16, max_blocks=4)
    assert k40 == PINNED[(tuple(range(32)), 0)]
    # ...and prompts sharing the first 4 blocks collide regardless of tail.
    base = list(range(64))
    k_a = prompt_affinity_key(base + [100, 101] * 8, block_size=16,
                              max_blocks=4)
    k_b = prompt_affinity_key(base + [200, 201] * 20, block_size=16,
                              max_blocks=4)
    assert k_a == k_b == prompt_affinity_key(base, block_size=16,
                                             max_blocks=4)


def test_prompt_affinity_key_sub_block_is_none():
    assert prompt_affinity_key([1, 2, 3], block_size=16) is None
    assert prompt_affinity_key([], block_size=16) is None


def test_stable_hash_bytes():
    assert stable_hash(b"replica-0:0") == 6839600686454068614


def test_prefix_uses_shared_key():
    p = Prefix(tuple(range(32)), block_size=16, lora_int_id=0)
    assert p.hash == PINNED[(tuple(range(32)), 0)]
    # builtin hash() folds large ints mod 2**61-1; equal keys stay equal.
    assert hash(p) == hash(p.hash)


def test_prefix_pool_dedups_on_shared_key():
    pool = PrefixPool(block_size=16)
    a = pool.add_or_get_prefix(list(range(40)))
    b = pool.add_or_get_prefix(list(range(32)))
    assert a is b
    assert a.hash == PINNED[(tuple(range(32)), 0)]
    # Different adapters never share a pool entry.
    c = pool.add_or_get_prefix(list(range(32)), lora_int_id=3)
    assert c is not a
