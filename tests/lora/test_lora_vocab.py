"""Extra-vocabulary LoRA tests (embed_tokens / lm_head adapters).

Reference roles: `vllm/lora/layers.py:147` VocabParallelEmbeddingWithLoRA,
`:783` SamplerWithLoRA, `vllm/config.py:453-465` lora_extra_vocab_size,
and the new_embeddings.safetensors convention. Golden strategy: an engine
serving the adapter must emit the same greedy tokens as a plain engine
serving a checkpoint with the adapter merged AND the vocabulary resized
(extra rows appended to embed_tokens/lm_head).
"""
import json
import os

import numpy as np
import pytest

from intellillm_tpu.lora.request import LoRARequest
from intellillm_tpu.sampling_params import SamplingParams

_E = 64          # tiny-llama hidden size (tests/conftest.py)
_LAYERS = 2
_RANK = 8
_ALPHA = 8.0
_EXTRA = 4


def _base_vocab(base_dir) -> int:
    with open(os.path.join(base_dir, "config.json")) as f:
        return json.load(f)["vocab_size"]


def _make_vocab_adapter(base_dir, out_dir, seed=0):
    """PEFT adapter with q/v projections + embed_tokens + lm_head targets,
    new_embeddings rows, and a vocabulary-extended tokenizer."""
    import safetensors.numpy
    from transformers import AutoTokenizer

    v = _base_vocab(base_dir)
    rng = np.random.RandomState(seed)
    t = {}
    for li in range(_LAYERS):
        for name, dout in (("q_proj", _E), ("v_proj", 32)):
            base = f"base_model.model.model.layers.{li}.self_attn.{name}"
            t[f"{base}.lora_A.weight"] = rng.randn(
                _RANK, _E).astype(np.float32) * 0.1
            t[f"{base}.lora_B.weight"] = rng.randn(
                dout, _RANK).astype(np.float32) * 0.1
    # PEFT Embedding layout: A [r, vocab], B [hidden, r].
    t["base_model.model.model.embed_tokens.lora_embedding_A"] = \
        rng.randn(_RANK, v).astype(np.float32) * 0.1
    t["base_model.model.model.embed_tokens.lora_embedding_B"] = \
        rng.randn(_E, _RANK).astype(np.float32) * 0.1
    # PEFT Linear layout: A [r, hidden], B [vocab, r].
    t["base_model.lm_head.lora_A.weight"] = rng.randn(
        _RANK, _E).astype(np.float32) * 0.1
    t["base_model.lm_head.lora_B.weight"] = rng.randn(
        v, _RANK).astype(np.float32) * 0.1

    os.makedirs(out_dir, exist_ok=True)
    safetensors.numpy.save_file(
        t, os.path.join(out_dir, "adapter_model.safetensors"))
    # Extra-token rows. One output row is boosted so greedy generation
    # actually emits an extra-vocab id (proving the extra-logit path).
    inp = rng.randn(_EXTRA, _E).astype(np.float32) * 0.1
    outp = rng.randn(_EXTRA, _E).astype(np.float32) * 0.1
    outp[1] *= 40.0
    safetensors.numpy.save_file(
        {"input_embeddings": inp, "output_embeddings": outp},
        os.path.join(out_dir, "new_embeddings.safetensors"))
    with open(os.path.join(out_dir, "adapter_config.json"), "w") as f:
        json.dump({"r": _RANK, "lora_alpha": _ALPHA,
                   "target_modules": ["q_proj", "v_proj", "embed_tokens",
                                      "lm_head"]}, f)
    tok = AutoTokenizer.from_pretrained(base_dir)
    tok.add_tokens([f"<extra{i}>" for i in range(_EXTRA)])
    tok.save_pretrained(out_dir)
    return out_dir


def _make_vocab_merged(base_dir, adapter_dir, out_dir):
    """Golden twin: vocab resized to v+extra, adapter merged into the
    base weights, extra rows written verbatim."""
    import safetensors.numpy
    import torch
    from transformers import AutoModelForCausalLM, AutoTokenizer

    v = _base_vocab(base_dir)
    model = AutoModelForCausalLM.from_pretrained(base_dir,
                                                 torch_dtype=torch.float32)
    t = safetensors.numpy.load_file(
        os.path.join(adapter_dir, "adapter_model.safetensors"))
    extra = safetensors.numpy.load_file(
        os.path.join(adapter_dir, "new_embeddings.safetensors"))
    scaling = _ALPHA / _RANK

    model.resize_token_embeddings(v + _EXTRA)
    sd = model.state_dict()
    for name, arr in t.items():
        if ".lora_A." not in name or "lm_head" in name:
            continue
        b_arr = t[name.replace(".lora_A.", ".lora_B.")]
        target = name.replace("base_model.model.", "").replace(
            ".lora_A.weight", ".weight")
        sd[target] += torch.from_numpy(
            (scaling * (b_arr @ arr)).astype(np.float32))
    ea = t["base_model.model.model.embed_tokens.lora_embedding_A"]
    eb = t["base_model.model.model.embed_tokens.lora_embedding_B"]
    sd["model.embed_tokens.weight"][:v] += torch.from_numpy(
        (scaling * (eb @ ea)).T.astype(np.float32))
    sd["model.embed_tokens.weight"][v:] = torch.from_numpy(
        extra["input_embeddings"])
    ha = t["base_model.lm_head.lora_A.weight"]
    hb = t["base_model.lm_head.lora_B.weight"]
    sd["lm_head.weight"][:v] += torch.from_numpy(
        (scaling * (hb @ ha)).astype(np.float32))
    sd["lm_head.weight"][v:] = torch.from_numpy(
        extra["output_embeddings"])
    model.load_state_dict(sd)
    model.save_pretrained(out_dir, safe_serialization=True)
    tok = AutoTokenizer.from_pretrained(base_dir)
    tok.add_tokens([f"<extra{i}>" for i in range(_EXTRA)])
    tok.save_pretrained(out_dir)
    return out_dir


@pytest.fixture(scope="module")
def vocab_setup(tiny_llama_dir, tmp_path_factory):
    root = tmp_path_factory.mktemp("lora-vocab")
    ad = _make_vocab_adapter(tiny_llama_dir, str(root / "ad"))
    merged = _make_vocab_merged(tiny_llama_dir, ad, str(root / "merged"))
    return dict(base=tiny_llama_dir, ad=ad, merged=merged)


def _greedy(model_dir, prompts, lora_request=None, **kw):
    from intellillm_tpu.entrypoints.llm import LLM
    llm = LLM(model=model_dir, max_model_len=64,
              num_device_blocks_override=64, **kw)
    outs = llm.generate(prompts, SamplingParams(temperature=0.0,
                                                max_tokens=8),
                        lora_request=lora_request)
    return [(o.outputs[0].token_ids, o.outputs[0].text) for o in outs]


def test_extra_vocab_lora_matches_resized_merged_twin(vocab_setup,
                                                      example_prompts):
    """Adapter-extended vocabulary end to end: prompts containing added
    tokens, embed/lm_head deltas, and extra-token logits must all match
    the merged+resized golden twin under greedy."""
    prompts = [p + " <extra0> <extra2>" for p in example_prompts[:3]]
    golden = _greedy(vocab_setup["merged"], prompts)
    ours = _greedy(vocab_setup["base"], prompts,
                   lora_request=LoRARequest("ad", 1, vocab_setup["ad"]),
                   enable_lora=True, max_loras=2, max_lora_rank=_RANK,
                   lora_extra_vocab_size=_EXTRA)
    v = _base_vocab(vocab_setup["base"])
    emitted = [tid for ids, _ in ours for tid in ids]
    assert any(tid >= v for tid in emitted), (
        "boosted extra token never sampled — extra-logit path untested")
    for (g_ids, g_text), (o_ids, o_text) in zip(golden, ours):
        assert o_ids == g_ids
        assert o_text == g_text


def test_extra_vocab_rows_isolated_per_adapter(vocab_setup,
                                               example_prompts):
    """A no-adapter request in the same batch must NEVER sample an
    extra-vocab id (its extra logits are masked to -inf), even while a
    sibling row's adapter boosts one."""
    from intellillm_tpu.entrypoints.llm import LLM

    llm = LLM(model=vocab_setup["base"], max_model_len=64,
              num_device_blocks_override=64, enable_lora=True, max_loras=2,
              max_lora_rank=_RANK, lora_extra_vocab_size=_EXTRA)
    params = SamplingParams(temperature=0.0, max_tokens=8)
    engine = llm.llm_engine
    engine.add_request("0", example_prompts[0], params,
                       lora_request=LoRARequest("ad", 1, vocab_setup["ad"]))
    engine.add_request("1", example_prompts[0], params)
    outs = {o.request_id: o for o in llm._run_engine(use_tqdm=False)}
    v = _base_vocab(vocab_setup["base"])
    assert all(t < v for t in outs["1"].outputs[0].token_ids)
    assert any(t >= v for t in outs["0"].outputs[0].token_ids)


def test_vocab_adapter_rejected_when_extra_vocab_disabled(vocab_setup,
                                                          example_prompts):
    from intellillm_tpu.entrypoints.llm import LLM

    llm = LLM(model=vocab_setup["base"], max_model_len=64,
              num_device_blocks_override=64, enable_lora=True,
              max_lora_rank=_RANK, lora_extra_vocab_size=0)
    with pytest.raises(ValueError, match="extra-vocab"):
        llm.llm_engine.add_request(
            "0", example_prompts[0], SamplingParams(max_tokens=4),
            lora_request=LoRARequest("ad", 1, vocab_setup["ad"]))