"""Multi-LoRA subsystem tests.

Reference test roles: `tests/lora/test_layers.py` (layer-level equivalence
vs manually applied adapters), `test_lora_manager.py` (LRU behavior),
`test_llama.py` (end-to-end llama + LoRA). Golden strategy here: an engine
serving adapter X must emit the same greedy tokens as a plain engine
serving a checkpoint with X *merged into the base weights* (W += s·BA).
"""
import json
import os

import numpy as np
import pytest

from intellillm_tpu.lora.layers import lora_delta
from intellillm_tpu.lora.models import LoRAModel, LoRAModelManager
from intellillm_tpu.lora.request import LoRARequest
from intellillm_tpu.sampling_params import SamplingParams

TARGETS = ("q_proj", "k_proj", "v_proj", "o_proj", "gate_proj", "up_proj",
           "down_proj")
# tiny-llama dims (tests/conftest.py): hidden 64, 4 heads, 2 kv heads,
# intermediate 128, 2 layers.
_DIMS = {
    "q_proj": (64, 64),
    "k_proj": (64, 32),
    "v_proj": (64, 32),
    "o_proj": (64, 64),
    "gate_proj": (64, 128),
    "up_proj": (64, 128),
    "down_proj": (128, 64),
}
_NUM_LAYERS = 2


def make_adapter(out_dir: str, seed: int, rank: int, alpha: float,
                 targets=TARGETS) -> str:
    """Write an HF-PEFT-style adapter directory."""
    import safetensors.numpy
    rng = np.random.RandomState(seed)
    tensors = {}
    for li in range(_NUM_LAYERS):
        for t in targets:
            din, dout = _DIMS[t]
            mod = "self_attn" if t.startswith(("q_", "k_", "v_", "o_")) \
                else "mlp"
            base = f"base_model.model.model.layers.{li}.{mod}.{t}"
            tensors[f"{base}.lora_A.weight"] = rng.randn(
                rank, din).astype(np.float32) * 0.1
            tensors[f"{base}.lora_B.weight"] = rng.randn(
                dout, rank).astype(np.float32) * 0.1
    os.makedirs(out_dir, exist_ok=True)
    safetensors.numpy.save_file(tensors,
                                os.path.join(out_dir,
                                             "adapter_model.safetensors"))
    with open(os.path.join(out_dir, "adapter_config.json"), "w") as f:
        json.dump({"r": rank, "lora_alpha": alpha,
                   "target_modules": list(targets)}, f)
    return out_dir


def make_merged_checkpoint(base_dir: str, adapter_dir: str,
                           out_dir: str) -> str:
    """Base checkpoint with the adapter merged: W += (alpha/r)·B@A."""
    import torch
    from transformers import AutoModelForCausalLM, AutoTokenizer
    import safetensors.numpy

    model = AutoModelForCausalLM.from_pretrained(base_dir,
                                                 torch_dtype=torch.float32)
    with open(os.path.join(adapter_dir, "adapter_config.json")) as f:
        cfg = json.load(f)
    scaling = cfg["lora_alpha"] / cfg["r"]
    tensors = safetensors.numpy.load_file(
        os.path.join(adapter_dir, "adapter_model.safetensors"))

    sd = model.state_dict()
    for name, arr in tensors.items():
        if ".lora_A." not in name:
            continue
        b_arr = tensors[name.replace(".lora_A.", ".lora_B.")]
        target = name.replace("base_model.model.", "").replace(
            ".lora_A.weight", ".weight")
        sd[target] += torch.from_numpy(
            (scaling * (b_arr @ arr)).astype(np.float32))
    model.load_state_dict(sd)
    model.save_pretrained(out_dir, safe_serialization=True)
    AutoTokenizer.from_pretrained(base_dir).save_pretrained(out_dir)
    return out_dir


# --- unit: the bgmv-equivalent op ---------------------------------------


def test_lora_delta_matches_per_row_loop():
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    s, din, r, dout, b, l = 3, 16, 4, 24, 5, 7
    a = rng.randn(s, din, r).astype(np.float32)
    bb = rng.randn(s, r, dout).astype(np.float32)
    a[0] = 0.0
    bb[0] = 0.0
    x = rng.randn(b, l, din).astype(np.float32)
    slots = np.array([0, 1, 2, 1, 0], np.int32)

    out = np.asarray(lora_delta(jnp.asarray(x), jnp.asarray(a),
                                jnp.asarray(bb), jnp.asarray(slots)))
    for i in range(b):
        ref = x[i] @ a[slots[i]] @ bb[slots[i]]
        np.testing.assert_allclose(out[i], ref, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(out[0], 0.0, atol=0)
    np.testing.assert_allclose(out[4], 0.0, atol=0)


# --- unit: checkpoint loading + manager ----------------------------------


def test_lora_model_from_checkpoint(tmp_path):
    d = make_adapter(str(tmp_path / "ad"), seed=0, rank=4, alpha=8.0)
    lora = LoRAModel.from_local_checkpoint(d, num_layers=_NUM_LAYERS)
    assert lora.rank == 4
    assert set(lora.targets) == {"q", "k", "v", "o", "gate", "up", "down"}
    a, b = lora.layers[0]["q"]
    assert a.shape == (64, 4) and b.shape == (4, 64)
    # B pre-scaled by alpha/r = 2.
    raw = np.asarray(
        __import__("safetensors.numpy", fromlist=["numpy"]).load_file(
            os.path.join(d, "adapter_model.safetensors"))
        ["base_model.model.model.layers.0.self_attn.q_proj.lora_B.weight"])
    np.testing.assert_allclose(b, raw.T * 2.0, rtol=1e-6)


def test_manager_lru_eviction(tmp_path):
    dims = {"q": (64, 64), "v": (64, 32)}
    mgr = LoRAModelManager(num_layers=_NUM_LAYERS, target_dims=dims,
                           max_loras=2, max_lora_rank=8, dtype="float32")
    loras = {}
    for i in (1, 2, 3):
        d = make_adapter(str(tmp_path / f"ad{i}"), seed=i, rank=4,
                         alpha=4.0, targets=("q_proj", "v_proj"))
        loras[i] = LoRAModel.from_local_checkpoint(d, _NUM_LAYERS)

    mgr.begin_batch()
    s1 = mgr.activate(1, loras[1])
    s2 = mgr.activate(2, loras[2])
    assert {s1, s2} == {1, 2}
    # Touch 1 so 2 becomes LRU; activating 3 (in a later batch) must evict 2.
    mgr.slot_of(1)
    mgr.begin_batch()
    s3 = mgr.activate(3, loras[3])
    assert s3 == s2
    assert mgr.is_active(1) and mgr.is_active(3) and not mgr.is_active(2)
    # Slot content: stack row equals padded adapter weights.
    a_dev = np.asarray(mgr.a_stacks["q"][0, s3])
    np.testing.assert_allclose(a_dev[:, :4], loras[3].layers[0]["q"][0],
                               rtol=1e-6)
    np.testing.assert_allclose(a_dev[:, 4:], 0.0, atol=0)
    # Slot 0 stays all-zero.
    np.testing.assert_allclose(np.asarray(mgr.a_stacks["q"][:, 0]), 0.0,
                               atol=0)


def test_manager_rejects_oversize_rank(tmp_path):
    d = make_adapter(str(tmp_path / "ad"), seed=0, rank=16, alpha=16.0,
                     targets=("q_proj", ))
    lora = LoRAModel.from_local_checkpoint(d, _NUM_LAYERS)
    mgr = LoRAModelManager(num_layers=_NUM_LAYERS,
                           target_dims={"q": (64, 64)}, max_loras=1,
                           max_lora_rank=8, dtype="float32")
    with pytest.raises(ValueError, match="max_lora_rank"):
        mgr.activate(1, lora)


def test_rslora_scaling(tmp_path):
    d = make_adapter(str(tmp_path / "ad"), seed=0, rank=4, alpha=8.0,
                     targets=("q_proj", ))
    with open(os.path.join(d, "adapter_config.json")) as f:
        cfg = json.load(f)
    cfg["use_rslora"] = True
    with open(os.path.join(d, "adapter_config.json"), "w") as f:
        json.dump(cfg, f)
    lora = LoRAModel.from_local_checkpoint(d, _NUM_LAYERS)
    import safetensors.numpy
    raw = safetensors.numpy.load_file(
        os.path.join(d, "adapter_model.safetensors"))
    b_raw = raw["base_model.model.model.layers.0.self_attn.q_proj"
                ".lora_B.weight"]
    np.testing.assert_allclose(lora.layers[0]["q"][1],
                               b_raw.T * (8.0 / 2.0), rtol=1e-6)


def test_prefix_pool_keyed_by_lora_id():
    from intellillm_tpu.prefix import PrefixPool
    pool = PrefixPool(block_size=4)
    p_base = pool.add_or_get_prefix([1, 2, 3, 4], 0)
    p_lora = pool.add_or_get_prefix([1, 2, 3, 4], 1)
    assert p_base is not p_lora
    assert pool.add_or_get_prefix([1, 2, 3, 4], 0) is p_base
    assert pool.add_or_get_prefix([1, 2, 3, 4], 1) is p_lora


# --- end-to-end: engine + adapters vs merged checkpoints -----------------


@pytest.fixture(scope="module")
def lora_setup(tmp_path_factory):
    """Base tiny llama + two adapters + their merged golden checkpoints."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    root = tmp_path_factory.mktemp("lora-e2e")
    base = str(root / "base")
    from tests.conftest import _build_word_tokenizer
    _, vocab_size = _build_word_tokenizer(base)
    torch.manual_seed(0)
    config = LlamaConfig(
        vocab_size=vocab_size, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rms_norm_eps=1e-6, pad_token_id=0,
        eos_token_id=1, bos_token_id=1, tie_word_embeddings=False,
        torch_dtype=torch.float32)
    LlamaForCausalLM(config).eval().save_pretrained(
        base, safe_serialization=True)

    ad1 = make_adapter(str(root / "ad1"), seed=11, rank=4, alpha=8.0)
    ad2 = make_adapter(str(root / "ad2"), seed=22, rank=8, alpha=8.0,
                       targets=("q_proj", "v_proj"))
    merged1 = make_merged_checkpoint(base, ad1, str(root / "merged1"))
    merged2 = make_merged_checkpoint(base, ad2, str(root / "merged2"))
    return dict(base=base, ad1=ad1, ad2=ad2, merged1=merged1,
                merged2=merged2)


def _greedy_tokens(model_dir, prompts, max_tokens=8, **llm_kwargs):
    from intellillm_tpu.entrypoints.llm import LLM
    llm = LLM(model=model_dir, max_model_len=64,
              num_device_blocks_override=64, **llm_kwargs)
    params = SamplingParams(temperature=0.0, max_tokens=max_tokens)
    outs = llm.generate(prompts, params)
    return [o.outputs[0].token_ids for o in outs]


def test_engine_multi_lora_concurrent(lora_setup, example_prompts):
    """Rows with adapter 1, adapter 2, and no adapter run in the SAME
    batch; each must match its merged-checkpoint golden."""
    from intellillm_tpu.entrypoints.llm import LLM

    prompts = example_prompts[:3]
    golden_base = _greedy_tokens(lora_setup["base"], prompts)
    golden_1 = _greedy_tokens(lora_setup["merged1"], prompts)
    golden_2 = _greedy_tokens(lora_setup["merged2"], prompts)

    llm = LLM(model=lora_setup["base"], max_model_len=64,
              num_device_blocks_override=64, enable_lora=True, max_loras=2,
              max_lora_rank=8)
    reqs = [
        LoRARequest("ad1", 1, lora_setup["ad1"]),
        LoRARequest("ad2", 2, lora_setup["ad2"]),
        None,
    ]
    params = SamplingParams(temperature=0.0, max_tokens=8)
    engine = llm.llm_engine
    for i, prompt in enumerate(prompts):
        for j, req in enumerate(reqs):
            engine.add_request(str(i * 10 + j), prompt, params,
                               lora_request=req)
    outputs = {o.request_id: o for o in llm._run_engine(use_tqdm=False)}

    for i in range(len(prompts)):
        assert outputs[str(i * 10)].outputs[0].token_ids == golden_1[i]
        assert outputs[str(i * 10 + 1)].outputs[0].token_ids == golden_2[i]
        assert outputs[str(i * 10 + 2)].outputs[0].token_ids == golden_base[i]


def test_engine_lora_lru_two_adapters_one_slot(lora_setup, example_prompts):
    """max_loras=1: serving adapter 1 then adapter 2 forces activation →
    eviction → activation; outputs stay correct for both."""
    from intellillm_tpu.entrypoints.llm import LLM

    prompt = example_prompts[0]
    golden_1 = _greedy_tokens(lora_setup["merged1"], [prompt])[0]
    golden_2 = _greedy_tokens(lora_setup["merged2"], [prompt])[0]

    llm = LLM(model=lora_setup["base"], max_model_len=64,
              num_device_blocks_override=64, enable_lora=True, max_loras=1,
              max_lora_rank=8)
    params = SamplingParams(temperature=0.0, max_tokens=8)
    out1 = llm.generate([prompt], params,
                        lora_request=LoRARequest("ad1", 1,
                                                 lora_setup["ad1"]))
    out2 = llm.generate([prompt], params,
                        lora_request=LoRARequest("ad2", 2,
                                                 lora_setup["ad2"]))
    assert out1[0].outputs[0].token_ids == golden_1
    assert out2[0].outputs[0].token_ids == golden_2
    mgr = llm.llm_engine.worker.lora_manager.device_manager
    assert mgr.is_active(2) and not mgr.is_active(1)


def test_scheduler_lora_admission_cap(lora_setup, example_prompts):
    """With max_loras=1, requests naming 2 distinct adapters still all
    complete (the scheduler defers, never starves)."""
    from intellillm_tpu.entrypoints.llm import LLM

    llm = LLM(model=lora_setup["base"], max_model_len=64,
              num_device_blocks_override=64, enable_lora=True, max_loras=1,
              max_lora_rank=8)
    params = SamplingParams(temperature=0.0, max_tokens=4)
    engine = llm.llm_engine
    reqs = [LoRARequest("ad1", 1, lora_setup["ad1"]),
            LoRARequest("ad2", 2, lora_setup["ad2"])]
    for i, prompt in enumerate(example_prompts):
        engine.add_request(str(i), prompt, params,
                           lora_request=reqs[i % 2])
    outputs = llm._run_engine(use_tqdm=False)
    assert len(outputs) == len(example_prompts)
    assert all(o.finished for o in outputs)


def test_lora_request_rejected_when_disabled(lora_setup, example_prompts):
    from intellillm_tpu.entrypoints.llm import LLM

    llm = LLM(model=lora_setup["base"], max_model_len=64,
              num_device_blocks_override=64)
    with pytest.raises(ValueError, match="LoRA is not enabled"):
        llm.llm_engine.add_request(
            "0", example_prompts[0], SamplingParams(max_tokens=4),
            lora_request=LoRARequest("ad1", 1, lora_setup["ad1"]))


def test_lora_unsupported_model(tiny_opt_dir):
    from intellillm_tpu.entrypoints.llm import LLM

    with pytest.raises(ValueError, match="does not support LoRA"):
        LLM(model=tiny_opt_dir, max_model_len=64,
            num_device_blocks_override=64, enable_lora=True)


def test_lora_preemption_recompute_preserves_outputs(lora_setup,
                                                     example_prompts,
                                                     monkeypatch):
    """LoRA x preemption (VERDICT r3 item 9): a memory-pressured engine
    serving adapters must recompute preempted rows THROUGH the adapter
    and reproduce the unpressured outputs exactly."""
    from intellillm_tpu.core import scheduler as sched_mod
    from intellillm_tpu.entrypoints.llm import LLM

    prompts = example_prompts[:4]
    params = SamplingParams(temperature=0.0, max_tokens=48,
                            ignore_eos=True)
    reqs = [LoRARequest("ad1", 1, lora_setup["ad1"]),
            LoRARequest("ad2", 2, lora_setup["ad2"])]

    def run(blocks):
        llm = LLM(model=lora_setup["base"], max_model_len=128,
                  num_device_blocks_override=blocks, max_num_seqs=8,
                  max_paddings=512, swap_space=0.01, enable_lora=True,
                  max_loras=2, max_lora_rank=8)
        engine = llm.llm_engine
        for i, p in enumerate(prompts):
            engine.add_request(str(i), p, params,
                               lora_request=reqs[i % 2])
        outs = {o.request_id: o for o in llm._run_engine(use_tqdm=False)}
        return [outs[str(i)].outputs[0].token_ids
                for i in range(len(prompts))]

    roomy = run(128)

    preemptions = {"n": 0}
    orig = sched_mod.Scheduler._preempt_by_recompute

    def counting(self, seq_group):
        preemptions["n"] += 1
        return orig(self, seq_group)

    monkeypatch.setattr(sched_mod.Scheduler, "_preempt_by_recompute",
                        counting)
    tight = run(10)
    assert preemptions["n"] > 0, (
        "pool sized to force recompute preemption but none happened")
    assert tight == roomy


def test_lora_swap_preemption_preserves_outputs(lora_setup,
                                                example_prompts,
                                                monkeypatch):
    """LoRA x swap: best_of groups preempt by swap-out/swap-in; restored
    KV must continue generating under the right adapter."""
    from intellillm_tpu.core import scheduler as sched_mod
    from intellillm_tpu.entrypoints.llm import LLM

    prompts = example_prompts[:3]
    params = SamplingParams(temperature=0.8, top_p=0.9, best_of=2, n=1,
                            max_tokens=32, ignore_eos=True)
    req = LoRARequest("ad1", 1, lora_setup["ad1"])

    def run(blocks):
        llm = LLM(model=lora_setup["base"], max_model_len=128,
                  num_device_blocks_override=blocks, max_num_seqs=8,
                  max_paddings=512, swap_space=0.01, enable_lora=True,
                  max_loras=2, max_lora_rank=8, seed=0)
        engine = llm.llm_engine
        for i, p in enumerate(prompts):
            engine.add_request(str(i), p, params, lora_request=req)
        outs = {o.request_id: o for o in llm._run_engine(use_tqdm=False)}
        return [outs[str(i)].outputs[0].token_ids
                for i in range(len(prompts))]

    roomy = run(128)

    swaps = {"n": 0}
    orig = sched_mod.Scheduler._preempt_by_swap

    def counting(self, seq_group, blocks_to_swap_out):
        swaps["n"] += 1
        return orig(self, seq_group, blocks_to_swap_out)

    monkeypatch.setattr(sched_mod.Scheduler, "_preempt_by_swap", counting)
    tight = run(12)
    assert swaps["n"] > 0, (
        "pool sized to force swap preemption but none happened")
    assert tight == roomy
