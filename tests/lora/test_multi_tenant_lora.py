"""Multi-tenant multi-LoRA serving tests (docs/multitenancy.md).

Covers the tenancy-facing contracts of the LoRA subsystem: slot-0 rows
through a LoRA-ENABLED engine are bit-identical to a LoRA-off engine
(the reserved all-zero slot adds exact 0.0), the worker manager's LRU
churn under slot pressure never unpins adapters referenced by the
current batch, device/host evictions attribute to the owning tenant's
churn counters, and adapter traffic compiles no new executables beyond
the shape buckets the base engine already owns.
"""
import pytest

from intellillm_tpu import tenancy
from intellillm_tpu.config import LoRAConfig
from intellillm_tpu.lora.request import LoRARequest
from intellillm_tpu.lora.worker_manager import WorkerLoRAManager
from intellillm_tpu.sampling_params import SamplingParams
from intellillm_tpu.tenancy import TenantSpec, get_tenant_registry

from tests.lora.test_lora import _NUM_LAYERS, _greedy_tokens, make_adapter


@pytest.fixture(autouse=True)
def clean_tenancy():
    tenancy.reset_for_testing()
    yield
    tenancy.reset_for_testing()


@pytest.fixture(scope="module")
def mt_setup(tmp_path_factory):
    """Tiny base llama + three small adapters (q/v only, rank 4)."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    root = tmp_path_factory.mktemp("mt-lora")
    base = str(root / "base")
    from tests.conftest import _build_word_tokenizer
    _, vocab_size = _build_word_tokenizer(base)
    torch.manual_seed(0)
    config = LlamaConfig(
        vocab_size=vocab_size, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rms_norm_eps=1e-6, pad_token_id=0,
        eos_token_id=1, bos_token_id=1, tie_word_embeddings=False,
        torch_dtype=torch.float32)
    LlamaForCausalLM(config).eval().save_pretrained(
        base, safe_serialization=True)
    ads = {
        i: make_adapter(str(root / f"ad{i}"), seed=30 + i, rank=4,
                        alpha=8.0, targets=("q_proj", "v_proj"))
        for i in (1, 2, 3)
    }
    return dict(base=base, adapters=ads)


# --- satellite: slot-0 bit-equality --------------------------------------


def test_slot0_rows_bit_identical_to_lora_off_engine(mt_setup,
                                                     example_prompts):
    """Enabling LoRA must be free for base-model tenants: the same
    prompts through a LoRA-enabled engine WITHOUT an adapter emit
    token-for-token the outputs of a plain engine (slot 0's zero
    einsum adds exact 0.0 — greedy argmax cannot flip)."""
    prompts = example_prompts[:3]
    golden = _greedy_tokens(mt_setup["base"], prompts)
    via_lora_engine = _greedy_tokens(mt_setup["base"], prompts,
                                     enable_lora=True, max_loras=2,
                                     max_lora_rank=8)
    assert via_lora_engine == golden


def test_nonzero_adapter_changes_greedy_output(mt_setup, example_prompts):
    """Sanity leg of the bit-equality golden: the adapter path is live —
    a real (random, scaled) adapter flips at least one greedy token."""
    from intellillm_tpu.entrypoints.llm import LLM

    prompts = example_prompts[:3]
    golden = _greedy_tokens(mt_setup["base"], prompts)
    llm = LLM(model=mt_setup["base"], max_model_len=64,
              num_device_blocks_override=64, enable_lora=True, max_loras=2,
              max_lora_rank=8)
    outs = llm.generate(
        prompts, SamplingParams(temperature=0.0, max_tokens=8),
        lora_request=LoRARequest("ad1", 1, mt_setup["adapters"][1]))
    adapted = [o.outputs[0].token_ids for o in outs]
    assert adapted != golden


# --- satellite: worker-manager LRU churn under slot pressure --------------


class _FakeLoRAModel:
    supports_lora = True
    num_layers = _NUM_LAYERS
    hidden_size = 64

    class config:
        vocab_size = 0

    def lora_target_dims(self):
        return {"q": (64, 64), "v": (64, 32)}


def _manager(max_loras=2, max_cpu_loras=None):
    cfg = LoRAConfig(max_lora_rank=8, max_loras=max_loras,
                     max_cpu_loras=max_cpu_loras, lora_dtype="float32",
                     lora_extra_vocab_size=0)
    return WorkerLoRAManager(_FakeLoRAModel(), cfg)


def _req(mt_setup, i):
    return LoRARequest(f"ad{i}", i, mt_setup["adapters"][i])


def test_lru_churn_keeps_in_flight_slots_pinned(mt_setup):
    """Evict/reload under slot pressure: the batch-spanning adapter
    keeps its slot across the churn; only the LRU non-batch adapter is
    evicted; a batch needing more adapters than slots fails loudly
    instead of silently corrupting a pinned row."""
    mgr = _manager(max_loras=2, max_cpu_loras=4)
    r1, r2, r3 = (_req(mt_setup, i) for i in (1, 2, 3))

    state = mgr.set_active_loras([r1, r2, None, None], 4)
    slots1 = [int(x) for x in state["row_slots"]]
    assert sorted(slots1[:2]) == [1, 2] and slots1[2:] == [0, 0]

    # Next batch touches ad1 first then needs a slot for ad3: ad2 (LRU,
    # not in this batch) is evicted; ad1's slot must not move.
    state = mgr.set_active_loras([r1, r3], 2)
    slots2 = [int(x) for x in state["row_slots"]]
    assert slots2[0] == slots1[0], "in-flight adapter's slot moved"
    assert slots2[1] == slots1[1], "freed slot should be reused for ad3"
    dm = mgr.device_manager
    assert dm.is_active(1) and dm.is_active(3) and not dm.is_active(2)

    # Three distinct adapters in ONE batch with two slots: every
    # resident adapter is pinned by this batch → loud failure.
    with pytest.raises(RuntimeError, match="pinned by the current batch"):
        mgr.set_active_loras([r1, r2, r3], 3)


def test_reload_after_eviction_round_trips(mt_setup):
    """An evicted adapter reactivates correctly from the host cache
    (same weights land in whatever slot it gets)."""
    import numpy as np
    mgr = _manager(max_loras=1, max_cpu_loras=4)
    r1, r2 = _req(mt_setup, 1), _req(mt_setup, 2)
    mgr.set_active_loras([r1], 1)
    a_before = np.asarray(mgr.device_manager.a_stacks["q"][:, 1]).copy()
    mgr.set_active_loras([r2], 1)           # evicts ad1
    mgr.set_active_loras([r1], 1)           # reloads ad1
    a_after = np.asarray(mgr.device_manager.a_stacks["q"][:, 1])
    np.testing.assert_array_equal(a_before, a_after)


def test_adapter_churn_attributes_to_tenant(mt_setup):
    """Device-slot and host-cache evictions count against the OWNING
    tenant (registry-resolved), not the tenant that triggered them."""
    for i, tid in ((1, "acme"), (2, "globex")):
        get_tenant_registry().register(
            TenantSpec(tid, lora_request=_req(mt_setup, i)))
    mgr = _manager(max_loras=1, max_cpu_loras=2)
    r1, r2, r3 = (_req(mt_setup, i) for i in (1, 2, 3))

    mgr.set_active_loras([r1], 1)
    mgr.set_active_loras([r2], 1)   # device-evicts ad1 (acme)
    summary = tenancy.get_tenant_stats().summary()
    assert summary["acme"]["adapter_loads"] == 1
    assert summary["acme"]["adapter_evictions"] == 1
    assert summary["globex"]["adapter_loads"] == 1
    assert summary["globex"]["adapter_evictions"] == 0

    # ad3 is nobody's adapter: its host load attributes to the fallback
    # tenant, and the host-cache eviction it forces (max_cpu_loras=2,
    # LRU is ad1) lands on acme again.
    mgr.set_active_loras([r3], 1)
    summary = tenancy.get_tenant_stats().summary()
    assert summary["adapter-3"]["adapter_loads"] == 1
    assert summary["acme"]["adapter_evictions"] == 2
    assert summary["globex"]["adapter_evictions"] == 1  # device evict


def test_hot_unload_frees_slot_and_counts_eviction(mt_setup):
    get_tenant_registry().register(
        TenantSpec("acme", lora_request=_req(mt_setup, 1)))
    mgr = _manager(max_loras=2, max_cpu_loras=4)
    mgr.set_active_loras([_req(mt_setup, 1)], 1)
    mgr.unload_adapter(1)
    assert not mgr.device_manager.is_active(1)
    assert mgr.list_loras() == []
    summary = tenancy.get_tenant_stats().summary()
    assert summary["acme"]["adapter_evictions"] == 1
    # Unloading an absent adapter is a no-op, not a double count.
    mgr.unload_adapter(1)
    assert tenancy.get_tenant_stats().summary()[
        "acme"]["adapter_evictions"] == 1


# --- compile stability: no per-adapter executables ------------------------


def test_no_new_executables_for_adapter_traffic(mt_setup, example_prompts):
    """The recompile-hazard contract (worker/model_runner.py): once the
    LoRA-enabled engine has compiled a shape bucket, serving ANY
    adapter through that bucket is a cache hit — `row_slots` and the
    stacked A/B tensors are data, never part of the jit key."""
    from intellillm_tpu.entrypoints.llm import LLM
    from intellillm_tpu.obs import get_compile_tracker

    prompt = example_prompts[0]
    params = SamplingParams(temperature=0.0, max_tokens=8)
    llm = LLM(model=mt_setup["base"], max_model_len=64,
              num_device_blocks_override=64, enable_lora=True, max_loras=2,
              max_lora_rank=8)
    # Warm the shape buckets once with base-model (slot 0) traffic.
    llm.generate([prompt], params)
    baseline = get_compile_tracker().snapshot()["compiles"]

    for i in (1, 2, 1):
        llm.generate([prompt], params,
                     lora_request=_req(mt_setup, i))
    after = get_compile_tracker().snapshot()["compiles"]
    assert after == baseline, (
        f"adapter traffic minted new executables: {baseline} -> {after}")
