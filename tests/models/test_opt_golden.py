"""Golden greedy parity: our engine vs HF transformers (fp32).

Reference pattern: `tests/models/test_models.py:16-41` (exact token
equality under greedy fp32).
"""
import pytest

from intellillm_tpu import LLM, SamplingParams

MAX_TOKENS = 24


def _engine_generate_greedy(model_dir, prompts, max_tokens):
    llm = LLM(model=model_dir,
              dtype="float32",
              num_device_blocks_override=128,
              max_model_len=128,
              max_num_seqs=8,
              max_paddings=512,
              swap_space=0.01)
    params = SamplingParams(temperature=0.0, max_tokens=max_tokens)
    outputs = llm.generate(prompts, params)
    return [o.outputs[0].token_ids for o in outputs]


def _trim_eos(ids, eos=1):
    out = []
    for t in ids:
        out.append(t)
        if t == eos:
            break
    return out


def test_opt_greedy_matches_hf(tiny_opt_dir, example_prompts, hf_runner):
    hf = hf_runner(tiny_opt_dir)
    hf_out = hf.generate_greedy(example_prompts, MAX_TOKENS)
    our_out = _engine_generate_greedy(tiny_opt_dir, example_prompts,
                                      MAX_TOKENS)
    for i, (h, o) in enumerate(zip(hf_out, our_out)):
        assert _trim_eos(h) == _trim_eos(o), (
            f"prompt {i}: hf={h} ours={o}")


def test_llama_greedy_matches_hf(tiny_llama_dir, example_prompts, hf_runner):
    hf = hf_runner(tiny_llama_dir)
    hf_out = hf.generate_greedy(example_prompts, MAX_TOKENS)
    our_out = _engine_generate_greedy(tiny_llama_dir, example_prompts,
                                      MAX_TOKENS)
    for i, (h, o) in enumerate(zip(hf_out, our_out)):
        assert _trim_eos(h) == _trim_eos(o), (
            f"prompt {i}: hf={h} ours={o}")
