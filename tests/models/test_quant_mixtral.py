"""QuantMixtral: GPTQ/AWQ-quantized Mixtral checkpoints serve losslessly.

Role parity: reference `vllm/model_executor/models/mixtral_quant.py`
(whole file) — per-expert quantized linears, TP-sharded. Here the
per-expert packed int4 tensors stack to [N, in/2, out] and dequantize
through the exact codes inside the MoE layer; attention projections go
through the shared load_linear resolution.
"""
import json
import os

import jax.numpy as jnp
import numpy as np
import pytest
import torch

MAX_TOKENS = 8


def _pack_rows_int32(m):
    in_, out = m.shape
    packed = np.zeros((in_ // 8, out), np.int32)
    for j in range(8):
        packed |= m[j::8].astype(np.int32) << (4 * j)
    return packed


def _pack_cols_int32(m):
    g, out = m.shape
    packed = np.zeros((g, out // 8), np.int32)
    for j in range(8):
        packed |= m[:, j::8].astype(np.int32) << (4 * j)
    return packed


def _gptq_quantize(w, group):
    """[in, out] fp → (qweight, qzeros, scales, g_idx, dequant)."""
    in_, out = w.shape
    g = in_ // group
    g_idx = (np.arange(in_) // group).astype(np.int32)
    wg = w.reshape(g, group, out)
    wmin, wmax = wg.min(1), wg.max(1)
    s = np.maximum((wmax - wmin) / 15.0, 1e-8).astype(np.float32)
    z = np.round(-wmin / s).clip(1, 15).astype(np.uint8)
    q = np.clip(np.round(w / s[g_idx] + z[g_idx]), 0, 15).astype(np.uint8)
    deq = (q.astype(np.float32) - z[g_idx]) * s[g_idx]
    return (_pack_rows_int32(q),
            _pack_cols_int32((z.astype(np.int32) - 1).astype(np.uint8)),
            s, g_idx, deq)


@pytest.fixture(scope="module")
def quant_mixtral_dirs(tmp_path_factory):
    """(gptq_dir, fp_twin_dir) tiny Mixtral checkpoints: experts AND
    attention projections GPTQ-quantized; twin holds the dequants."""
    import safetensors.numpy
    from tests.conftest import _build_word_tokenizer
    from transformers import (AutoTokenizer, MixtralConfig,
                              MixtralForCausalLM)

    base = tmp_path_factory.mktemp("quant-mixtral")
    d = str(base / "build")
    os.makedirs(d, exist_ok=True)
    _, vocab_size = _build_word_tokenizer(d)
    torch.manual_seed(0)
    config = MixtralConfig(
        vocab_size=vocab_size, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=128, tie_word_embeddings=False,
        pad_token_id=0, bos_token_id=1, eos_token_id=1,
        torch_dtype=torch.float32)
    model = MixtralForCausalLM(config)
    model.eval()
    sd = {k: v.numpy() for k, v in model.state_dict().items()}

    group = 16
    targets = [k for k in sd
               if ("experts." in k and k.endswith(".weight"))
               or (("self_attn" in k) and k.endswith("_proj.weight"))]
    tensors = {k: v for k, v in sd.items() if k not in targets}
    twin_sd = dict(sd)
    for name in targets:
        w = sd[name].T.astype(np.float32)
        qweight, qzeros, scales, g_idx, deq = _gptq_quantize(w, group)
        prefix = name[:-len(".weight")]
        tensors[prefix + ".qweight"] = qweight
        tensors[prefix + ".qzeros"] = qzeros
        tensors[prefix + ".scales"] = scales
        tensors[prefix + ".g_idx"] = g_idx
        twin_sd[name] = np.ascontiguousarray(deq.T.astype(np.float32))

    gq_dir = str(base / "gptq")
    os.makedirs(gq_dir, exist_ok=True)
    safetensors.numpy.save_file(
        {k: np.ascontiguousarray(v) for k, v in tensors.items()},
        os.path.join(gq_dir, "model.safetensors"))
    cfg = json.loads(config.to_json_string())
    cfg["architectures"] = ["QuantMixtralForCausalLM"]
    cfg["quantization_config"] = {"quant_method": "gptq", "bits": 4,
                                  "group_size": group, "desc_act": False}
    with open(os.path.join(gq_dir, "config.json"), "w") as f:
        json.dump(cfg, f)
    AutoTokenizer.from_pretrained(d).save_pretrained(gq_dir)

    twin_dir = str(base / "twin")
    model.load_state_dict({k: torch.from_numpy(np.ascontiguousarray(v))
                           for k, v in twin_sd.items()})
    model.save_pretrained(twin_dir, safe_serialization=True)
    AutoTokenizer.from_pretrained(d).save_pretrained(twin_dir)
    return gq_dir, twin_dir


def _greedy(model_dir, prompts, tp=1):
    from intellillm_tpu import LLM, SamplingParams
    llm = LLM(model=model_dir, dtype="float32",
              num_device_blocks_override=128, max_model_len=64,
              max_num_seqs=8, swap_space=0.01, tensor_parallel_size=tp)
    outs = llm.generate(prompts, SamplingParams(temperature=0.0,
                                                max_tokens=MAX_TOKENS))
    return [o.outputs[0].token_ids for o in outs]


def test_quant_mixtral_loads_int4_stacks(quant_mixtral_dirs):
    """Checkpoint loads WITHOUT NotImplementedError; expert stacks are
    packed int4 that dequantize bit-exactly to the fp twin's values."""
    from intellillm_tpu.config import ModelConfig
    from intellillm_tpu.layers.quantization import dequant_int4_stack
    from intellillm_tpu.models.model_loader import get_model

    gq_dir, twin_dir = quant_mixtral_dirs
    mc = ModelConfig(model=gq_dir, dtype="float32")
    assert mc.quantization == "gptq"
    _, params_q = get_model(mc)
    _, params_fp = get_model(ModelConfig(model=twin_dir, dtype="float32"))

    n_stacks = 0
    for lq, lf in zip(params_q["layers"], params_fp["layers"]):
        for wname in ("w1", "w2", "w3"):
            assert isinstance(lq[wname], dict), (
                f"{wname} did not load as a packed int4 stack")
            deq = np.asarray(dequant_int4_stack(
                {k: jnp.asarray(v) for k, v in lq[wname].items()},
                jnp.float32))
            np.testing.assert_array_equal(deq, np.asarray(lf[wname]))
            n_stacks += 1
        for p in ("q", "k", "v", "o"):
            assert isinstance(lq[p], dict) and "q4" in lq[p]
    assert n_stacks == 6


def test_quant_mixtral_greedy_matches_twin(quant_mixtral_dirs,
                                           example_prompts):
    gq_dir, twin_dir = quant_mixtral_dirs
    golden = _greedy(twin_dir, example_prompts)
    ours = _greedy(gq_dir, example_prompts)
    for g, o in zip(golden, ours):
        assert g[0] == o[0]           # first token exact; fp32-accum
        # order may diverge later — same contract as the AWQ/GPTQ tests


def test_quant_mixtral_tp2(quant_mixtral_dirs, example_prompts):
    """TP=2 on the virtual CPU mesh: sharded packed stacks produce the
    same greedy stream as single-chip."""
    gq_dir, _ = quant_mixtral_dirs
    single = _greedy(gq_dir, example_prompts)
    tp2 = _greedy(gq_dir, example_prompts, tp=2)
    assert tp2 == single


def test_dense_fallback_inherits_quant_sharding(quant_mixtral_dirs):
    """Dense-fallback leaves at quantized-spec paths (dummy weights,
    irregular layouts) must inherit the packed form's sharding instead of
    silently replicating multi-GiB expert stacks on TP meshes."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from intellillm_tpu.config import ModelConfig
    from intellillm_tpu.models.model_loader import get_model
    from intellillm_tpu.parallel.mesh import shard_params

    gq_dir, _ = quant_mixtral_dirs
    # Dummy load: quantization="gptq" but expert stacks come out DENSE.
    mc = ModelConfig(model=gq_dir, dtype="float32", load_format="dummy")
    model, params = get_model(mc, load_format="dummy")
    devs = np.array(jax.devices()[:2]).reshape(1, 2)
    mesh = Mesh(devs, ("data", "model"))
    placed = shard_params(params, mesh, model)
    w1 = placed["layers"][0]["w1"]
    assert not isinstance(w1, dict)          # really the dense fallback
    spec = w1.sharding.spec
    assert "model" in tuple(spec), (
        f"dense expert stack replicated instead of sharded: {spec}")
