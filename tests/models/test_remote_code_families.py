"""Trust-remote-code model families (baichuan, qwen-v1, chatglm,
deepseek, aquila) — tested without executing remote code.

Strategies (reference covers most of these only via trust_remote_code on
real checkpoints, which needs network):
- *Equivalence goldens*: baichuan-7B == llama with W_pack fused; qwen-v1
  == qwen2 with fused c_attn and renamed tensors. We convert a tiny
  HF-native checkpoint into the remote-code layout and require identical
  greedy tokens.
- *Prefill/decode self-consistency*: for archs with no HF-native twin
  (chatglm, deepseek, baichuan-ALiBi), generating N tokens then re-feeding
  prompt+prefix must reproduce the continuation — catches KV-cache layout,
  position, and rope bugs.
- *Config shims*: config.json with remote-code model_type parses without
  trust_remote_code.
"""
import json
import os

import numpy as np
import pytest
import torch

from tests.conftest import _build_word_tokenizer

MAX_TOKENS = 12


def _save_config(d, cfg: dict):
    with open(os.path.join(d, "config.json"), "w") as f:
        json.dump(cfg, f)


def _save_tensors(d, tensors):
    import safetensors.numpy
    safetensors.numpy.save_file(
        {k: np.ascontiguousarray(v) for k, v in tensors.items()},
        os.path.join(d, "model.safetensors"))


def _engine_greedy(model_dir, prompts, max_tokens=MAX_TOKENS):
    from intellillm_tpu import LLM, SamplingParams
    llm = LLM(model=model_dir, dtype="float32",
              num_device_blocks_override=128, max_model_len=128,
              max_num_seqs=8, max_paddings=512, swap_space=0.01)
    outs = llm.generate(prompts, SamplingParams(temperature=0.0,
                                                max_tokens=max_tokens))
    return [o.outputs[0].token_ids for o in outs]


def _dummy_engine_greedy(hf_config, prompt_ids_list, max_tokens):
    """Engine with random weights from an in-memory config (no tokenizer)."""
    from intellillm_tpu.config import (CacheConfig, ModelConfig,
                                       ParallelConfig, SchedulerConfig)
    from intellillm_tpu.engine.llm_engine import LLMEngine
    from intellillm_tpu.sampling_params import SamplingParams

    model_config = ModelConfig.from_hf_config(hf_config, dtype="float32",
                                              max_model_len=128,
                                              load_format="dummy")
    cache_config = CacheConfig(block_size=16,
                               num_device_blocks_override=128,
                               swap_space_gib=0.01)
    scheduler_config = SchedulerConfig(max_num_batched_tokens=2048,
                                       max_num_seqs=8, max_model_len=128,
                                       max_paddings=512)
    engine = LLMEngine(model_config, cache_config, ParallelConfig(),
                       scheduler_config, log_stats=False,
                       skip_tokenizer_init=True)
    params = SamplingParams(temperature=0.0, max_tokens=max_tokens,
                            ignore_eos=True)
    for i, ids in enumerate(prompt_ids_list):
        engine.add_request(str(i), None, params, prompt_token_ids=list(ids))
    results = {}
    while engine.has_unfinished_requests():
        for out in engine.step():
            if out.finished:
                results[out.request_id] = out.outputs[0].token_ids
    return [results[str(i)] for i in range(len(prompt_ids_list))]


def _check_self_consistency(hf_config, seed=0):
    """Continuations must be stable under prompt extension (prefill KV ==
    decode KV)."""
    rng = np.random.default_rng(seed)
    vocab = hf_config.vocab_size
    prompt = rng.integers(0, vocab, size=9).tolist()
    full = _dummy_engine_greedy(hf_config, [prompt], 8)[0]
    ext = _dummy_engine_greedy(hf_config, [prompt + full[:4]], 4)[0]
    assert ext == full[4:8], f"full={full} ext={ext}"


# --- baichuan: equivalence with llama ------------------------------------


@pytest.fixture(scope="module")
def baichuan_pair(tmp_path_factory):
    """(llama_dir, baichuan_dir) with identical math."""
    from transformers import LlamaConfig, LlamaForCausalLM

    root = tmp_path_factory.mktemp("baichuan-eq")
    llama_dir = str(root / "llama")
    _, vocab_size = _build_word_tokenizer(llama_dir)
    torch.manual_seed(0)
    config = LlamaConfig(
        vocab_size=vocab_size, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        max_position_embeddings=128, pad_token_id=0, bos_token_id=1,
        eos_token_id=1, tie_word_embeddings=False,
        torch_dtype=torch.float32)
    model = LlamaForCausalLM(config).eval()
    model.save_pretrained(llama_dir, safe_serialization=True)

    bc_dir = str(root / "baichuan")
    _build_word_tokenizer(bc_dir)
    sd = {k: v.numpy() for k, v in model.state_dict().items()}
    tensors = {
        "model.embed_tokens.weight": sd["model.embed_tokens.weight"],
        "model.norm.weight": sd["model.norm.weight"],
        "lm_head.weight": sd["lm_head.weight"],
    }
    for i in range(2):
        p = f"model.layers.{i}."
        tensors[p + "input_layernorm.weight"] = sd[
            p + "input_layernorm.weight"]
        tensors[p + "post_attention_layernorm.weight"] = sd[
            p + "post_attention_layernorm.weight"]
        tensors[p + "self_attn.W_pack.weight"] = np.concatenate([
            sd[p + "self_attn.q_proj.weight"],
            sd[p + "self_attn.k_proj.weight"],
            sd[p + "self_attn.v_proj.weight"]], axis=0)
        tensors[p + "self_attn.o_proj.weight"] = sd[
            p + "self_attn.o_proj.weight"]
        for t in ("gate_proj", "up_proj", "down_proj"):
            tensors[p + f"mlp.{t}.weight"] = sd[p + f"mlp.{t}.weight"]
    _save_tensors(bc_dir, tensors)
    _save_config(bc_dir, {
        "model_type": "baichuan",
        "architectures": ["BaiChuanForCausalLM"],
        "vocab_size": vocab_size, "hidden_size": 64,
        "intermediate_size": 128, "num_hidden_layers": 2,
        "num_attention_heads": 4, "hidden_act": "silu",
        "max_position_embeddings": 128, "rms_norm_eps": 1e-6,
        "pad_token_id": 0, "bos_token_id": 1, "eos_token_id": 1,
        "tie_word_embeddings": False,
    })
    return llama_dir, bc_dir


def test_baichuan_matches_llama_twin(baichuan_pair, example_prompts,
                                     hf_runner):
    llama_dir, bc_dir = baichuan_pair
    hf = hf_runner(llama_dir)
    golden = hf.generate_greedy(example_prompts, MAX_TOKENS)
    ours = _engine_greedy(bc_dir, example_prompts)
    for h, o in zip(golden, ours):
        assert list(h[:len(o)]) == list(o[:len(h)]) or h == o, \
            f"hf={h} ours={o}"


def test_baichuan_alibi_self_consistent():
    """13B-style (hidden != 4096 → ALiBi) has no HF twin; check KV-cache
    consistency on the dummy engine."""
    from intellillm_tpu.transformers_utils.configs import BaichuanConfig
    cfg = BaichuanConfig(vocab_size=128, hidden_size=80,
                         intermediate_size=128, num_hidden_layers=2,
                         num_attention_heads=4,
                         max_position_embeddings=128)
    cfg.architectures = ["BaichuanForCausalLM"]
    _check_self_consistency(cfg)


# --- qwen v1: equivalence with qwen2 -------------------------------------


@pytest.fixture(scope="module")
def qwen_pair(tmp_path_factory):
    from transformers import Qwen2Config, Qwen2ForCausalLM

    root = tmp_path_factory.mktemp("qwen-eq")
    q2_dir = str(root / "qwen2")
    _, vocab_size = _build_word_tokenizer(q2_dir)
    torch.manual_seed(0)
    config = Qwen2Config(
        vocab_size=vocab_size, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        max_position_embeddings=128, pad_token_id=0, bos_token_id=1,
        eos_token_id=1, tie_word_embeddings=False,
        torch_dtype=torch.float32)
    model = Qwen2ForCausalLM(config).eval()
    model.save_pretrained(q2_dir, safe_serialization=True)

    q1_dir = str(root / "qwen1")
    _build_word_tokenizer(q1_dir)
    sd = {k: v.numpy() for k, v in model.state_dict().items()}
    tensors = {
        "transformer.wte.weight": sd["model.embed_tokens.weight"],
        "transformer.ln_f.weight": sd["model.norm.weight"],
        "lm_head.weight": sd["lm_head.weight"],
    }
    for i in range(2):
        src = f"model.layers.{i}."
        dst = f"transformer.h.{i}."
        tensors[dst + "ln_1.weight"] = sd[src + "input_layernorm.weight"]
        tensors[dst + "ln_2.weight"] = sd[
            src + "post_attention_layernorm.weight"]
        tensors[dst + "attn.c_attn.weight"] = np.concatenate([
            sd[src + "self_attn.q_proj.weight"],
            sd[src + "self_attn.k_proj.weight"],
            sd[src + "self_attn.v_proj.weight"]], axis=0)
        tensors[dst + "attn.c_attn.bias"] = np.concatenate([
            sd[src + "self_attn.q_proj.bias"],
            sd[src + "self_attn.k_proj.bias"],
            sd[src + "self_attn.v_proj.bias"]], axis=0)
        tensors[dst + "attn.c_proj.weight"] = sd[
            src + "self_attn.o_proj.weight"]
        # QWen: w2 = gate, w1 = up.
        tensors[dst + "mlp.w2.weight"] = sd[src + "mlp.gate_proj.weight"]
        tensors[dst + "mlp.w1.weight"] = sd[src + "mlp.up_proj.weight"]
        tensors[dst + "mlp.c_proj.weight"] = sd[src + "mlp.down_proj.weight"]
    _save_tensors(q1_dir, tensors)
    _save_config(q1_dir, {
        "model_type": "qwen",
        "architectures": ["QWenLMHeadModel"],
        "vocab_size": vocab_size, "hidden_size": 64,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        # QWen configs store DOUBLE the ffn width here.
        "intermediate_size": 256,
        "layer_norm_epsilon": 1e-6, "kv_channels": 16,
        "rotary_emb_base": 10000, "seq_length": 128,
        "max_position_embeddings": 128, "no_bias": True,
        "bos_token_id": 1, "eos_token_id": 1,
        "tie_word_embeddings": False,
    })
    return q2_dir, q1_dir


def test_qwen_v1_matches_qwen2_twin(qwen_pair, example_prompts, hf_runner):
    q2_dir, q1_dir = qwen_pair
    hf = hf_runner(q2_dir)
    golden = hf.generate_greedy(example_prompts, MAX_TOKENS)
    ours = _engine_greedy(q1_dir, example_prompts)
    for h, o in zip(golden, ours):
        assert list(h[:len(o)]) == list(o[:len(h)]) or h == o, \
            f"hf={h} ours={o}"


# --- chatglm / deepseek: self-consistency --------------------------------


def test_chatglm_self_consistent():
    from intellillm_tpu.transformers_utils.configs import ChatGLMConfig
    cfg = ChatGLMConfig(num_layers=2, padded_vocab_size=128, hidden_size=64,
                        ffn_hidden_size=96, kv_channels=16,
                        num_attention_heads=4, seq_length=128,
                        multi_query_attention=True, multi_query_group_num=2)
    cfg.architectures = ["ChatGLMModel"]
    _check_self_consistency(cfg)


def test_deepseek_self_consistent():
    from intellillm_tpu.transformers_utils.configs import DeepseekConfig
    cfg = DeepseekConfig(vocab_size=128, hidden_size=64,
                         intermediate_size=128, moe_intermediate_size=32,
                         num_hidden_layers=3, num_attention_heads=4,
                         num_key_value_heads=2, n_shared_experts=2,
                         n_routed_experts=4, num_experts_per_tok=2,
                         first_k_dense_replace=1, moe_layer_freq=1,
                         norm_topk_prob=False, max_position_embeddings=128)
    cfg.architectures = ["DeepseekForCausalLM"]
    _check_self_consistency(cfg)


def test_deepseek_moe_routing_no_renorm():
    """Un-renormalized top-k routing vs a numpy loop (deepseek semantics
    differ from Mixtral exactly here)."""
    import jax.numpy as jnp
    from intellillm_tpu.layers.moe import moe_ffn_dense

    rng = np.random.RandomState(0)
    t, d, i, n, k = 10, 8, 16, 4, 2
    x = rng.randn(t, d).astype(np.float32)
    gate_w = rng.randn(d, n).astype(np.float32)
    w1 = rng.randn(n, d, i).astype(np.float32) * 0.1
    w2 = rng.randn(n, i, d).astype(np.float32) * 0.1
    w3 = rng.randn(n, d, i).astype(np.float32) * 0.1

    out = np.asarray(moe_ffn_dense(jnp.asarray(x), jnp.asarray(gate_w),
                                   jnp.asarray(w1), jnp.asarray(w2),
                                   jnp.asarray(w3), k, renormalize=False))

    def silu(v):
        return v / (1.0 + np.exp(-v))

    probs = np.exp(x @ gate_w)
    probs = probs / probs.sum(-1, keepdims=True)
    ref = np.zeros_like(x)
    for ti in range(t):
        top = np.argsort(-probs[ti])[:k]
        for e in top:
            h = silu(x[ti] @ w1[e]) * (x[ti] @ w3[e])
            ref[ti] += probs[ti, e] * (h @ w2[e])   # NO renormalization
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


# --- decilm: variable GQA == degrouped uniform-GQA llama -----------------


@pytest.fixture(scope="module")
def decilm_pair(tmp_path_factory):
    """(llama_dir, decilm_dir): the llama twin stores layer-0 K/V already
    degrouped (2 kv heads expanded to 4 — interleaved h0,h0,h1,h1, which
    is the only ordering consistent with grouped-query head mapping; a
    tile ordering would break this equivalence), the DeciLM checkpoint
    stores the grouped original + num_key_value_heads_per_layer=[2, 4].
    Degrouping is exact, so greedy tokens must match."""
    from transformers import LlamaConfig, LlamaForCausalLM

    root = tmp_path_factory.mktemp("decilm-eq")
    llama_dir = str(root / "llama")
    _, vocab_size = _build_word_tokenizer(llama_dir)
    torch.manual_seed(0)
    config = LlamaConfig(
        vocab_size=vocab_size, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        max_position_embeddings=128, pad_token_id=0, bos_token_id=1,
        eos_token_id=1, tie_word_embeddings=False,
        torch_dtype=torch.float32)
    model = LlamaForCausalLM(config).eval()
    head_size = 64 // 4
    with torch.no_grad():
        for t in ("k_proj", "v_proj"):
            w = getattr(model.model.layers[0].self_attn, t).weight
            grouped = w[:2 * head_size].clone()           # 2 kv heads
            degrouped = torch.repeat_interleave(
                grouped.reshape(2, head_size, -1), 2,
                dim=0).reshape(4 * head_size, -1)         # h0,h0,h1,h1
            w.copy_(degrouped)
    model.save_pretrained(llama_dir, safe_serialization=True)

    deci_dir = str(root / "decilm")
    _build_word_tokenizer(deci_dir)
    sd = {k: v.numpy() for k, v in model.state_dict().items()}
    tensors = dict(sd)
    for t in ("k_proj", "v_proj"):
        key = f"model.layers.0.self_attn.{t}.weight"
        tensors[key] = sd[key].reshape(
            2, 2, head_size, -1)[:, 0].reshape(2 * head_size, -1)
    _save_tensors(deci_dir, tensors)
    _save_config(deci_dir, {
        "model_type": "deci",
        "architectures": ["DeciLMForCausalLM"],
        "vocab_size": vocab_size, "hidden_size": 64,
        "intermediate_size": 128, "num_hidden_layers": 2,
        "num_attention_heads": 4,
        "num_key_value_heads_per_layer": [2, 4],
        "hidden_act": "silu", "max_position_embeddings": 128,
        "rms_norm_eps": 1e-6, "pad_token_id": 0, "bos_token_id": 1,
        "eos_token_id": 1, "tie_word_embeddings": False,
    })
    return llama_dir, deci_dir


def test_decilm_variable_gqa_matches_degrouped_llama(decilm_pair,
                                                     example_prompts,
                                                     hf_runner):
    llama_dir, deci_dir = decilm_pair
    hf = hf_runner(llama_dir)
    golden = hf.generate_greedy(example_prompts, MAX_TOKENS)
    ours = _engine_greedy(deci_dir, example_prompts)
    for h, o in zip(golden, ours):
        assert list(h[:len(o)]) == list(o[:len(h)]) or h == o, \
            f"hf={h} ours={o}"


# --- internlm: llama + attention biases ----------------------------------


@pytest.fixture(scope="module")
def internlm_pair(tmp_path_factory):
    """(llama_dir, internlm_dir) with identical math: HF llama with
    attention_bias=True vs the same tensors under model_type=internlm
    with bias=true."""
    from transformers import LlamaConfig, LlamaForCausalLM

    root = tmp_path_factory.mktemp("internlm-eq")
    llama_dir = str(root / "llama")
    _, vocab_size = _build_word_tokenizer(llama_dir)
    torch.manual_seed(0)
    config = LlamaConfig(
        vocab_size=vocab_size, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        max_position_embeddings=128, pad_token_id=0, bos_token_id=1,
        eos_token_id=1, tie_word_embeddings=False, attention_bias=True,
        torch_dtype=torch.float32)
    model = LlamaForCausalLM(config).eval()
    with torch.no_grad():
        # save_pretrained zero-initializes fresh biases; randomize so the
        # equivalence actually exercises them.
        for layer in model.model.layers:
            for t in ("q_proj", "k_proj", "v_proj", "o_proj"):
                getattr(layer.self_attn, t).bias.normal_(std=0.1)
    model.save_pretrained(llama_dir, safe_serialization=True)

    il_dir = str(root / "internlm")
    _build_word_tokenizer(il_dir)
    _save_tensors(il_dir,
                  {k: v.numpy() for k, v in model.state_dict().items()})
    _save_config(il_dir, {
        "model_type": "internlm",
        "architectures": ["InternLMForCausalLM"],
        "vocab_size": vocab_size, "hidden_size": 64,
        "intermediate_size": 128, "num_hidden_layers": 2,
        "num_attention_heads": 4, "bias": True, "hidden_act": "silu",
        "max_position_embeddings": 128, "rms_norm_eps": 1e-6,
        "pad_token_id": 0, "bos_token_id": 1, "eos_token_id": 1,
        "tie_word_embeddings": False,
    })
    return llama_dir, il_dir


def test_internlm_bias_matches_llama_twin(internlm_pair, example_prompts,
                                          hf_runner):
    llama_dir, il_dir = internlm_pair
    hf = hf_runner(llama_dir)
    golden = hf.generate_greedy(example_prompts, MAX_TOKENS)
    ours = _engine_greedy(il_dir, example_prompts)
    for h, o in zip(golden, ours):
        assert list(h[:len(o)]) == list(o[:len(h)]) or h == o, \
            f"hf={h} ours={o}"


# --- config shims --------------------------------------------------------


@pytest.mark.parametrize("model_type,extra", [
    ("baichuan", {"hidden_size": 64}),
    ("qwen", {"hidden_size": 64}),
    ("chatglm", {"hidden_size": 64}),
    ("deepseek", {"hidden_size": 64}),
    ("aquila", {"hidden_size": 64}),
    ("Yi", {"hidden_size": 64}),
    ("deci", {"hidden_size": 64,
              "num_key_value_heads_per_layer": [1, 2]}),
    ("internlm", {"hidden_size": 64, "bias": True}),
])
def test_config_shim_parses_without_remote_code(tmp_path, model_type,
                                                extra):
    from intellillm_tpu.transformers_utils.config import get_hf_config
    d = str(tmp_path / model_type)
    os.makedirs(d)
    cfg = {"model_type": model_type,
           "auto_map": {"AutoConfig": "configuration_x.XConfig"}}
    cfg.update(extra)
    _save_config(d, cfg)
    hf_config = get_hf_config(d, trust_remote_code=False)
    assert hf_config.model_type == model_type
    assert hf_config.hidden_size == 64
