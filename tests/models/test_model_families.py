"""Golden greedy parity vs HF transformers for each model family
(reference pattern: tests/models/test_models.py over 13 families)."""
import pytest
import torch

MAX_TOKENS = 16


def _build(tmp_path_factory, name, config_cls, model_cls, **cfg_kwargs):
    from tests.conftest import _build_word_tokenizer
    d = str(tmp_path_factory.mktemp(name))
    _, vocab_size = _build_word_tokenizer(d)
    torch.manual_seed(0)
    config = config_cls(vocab_size=vocab_size, **cfg_kwargs)
    model = model_cls(config)
    model.eval()
    model.save_pretrained(d, safe_serialization=True)
    return d


@pytest.fixture(scope="session")
def tiny_gpt2_dir(tmp_path_factory):
    from transformers import GPT2Config, GPT2LMHeadModel
    return _build(tmp_path_factory, "tiny-gpt2", GPT2Config, GPT2LMHeadModel,
                  n_embd=64, n_layer=2, n_head=4, n_positions=128,
                  bos_token_id=1, eos_token_id=1)


@pytest.fixture(scope="session")
def tiny_qwen2_dir(tmp_path_factory):
    from transformers import Qwen2Config, Qwen2ForCausalLM
    return _build(tmp_path_factory, "tiny-qwen2", Qwen2Config,
                  Qwen2ForCausalLM, hidden_size=64, intermediate_size=128,
                  num_hidden_layers=2, num_attention_heads=4,
                  num_key_value_heads=2, max_position_embeddings=128,
                  tie_word_embeddings=False, pad_token_id=0, bos_token_id=1,
                  eos_token_id=1)


@pytest.fixture(scope="session")
def tiny_mixtral_dir(tmp_path_factory):
    from transformers import MixtralConfig, MixtralForCausalLM
    return _build(tmp_path_factory, "tiny-mixtral", MixtralConfig,
                  MixtralForCausalLM, hidden_size=64, intermediate_size=96,
                  num_hidden_layers=2, num_attention_heads=4,
                  num_key_value_heads=2, num_local_experts=4,
                  num_experts_per_tok=2, max_position_embeddings=128,
                  tie_word_embeddings=False, pad_token_id=0, bos_token_id=1,
                  eos_token_id=1)


@pytest.fixture(scope="session")
def tiny_bloom_dir(tmp_path_factory):
    from transformers import BloomConfig, BloomForCausalLM
    return _build(tmp_path_factory, "tiny-bloom", BloomConfig,
                  BloomForCausalLM, hidden_size=64, n_layer=2, n_head=4,
                  bos_token_id=1, eos_token_id=1, pad_token_id=0)


@pytest.fixture(scope="session")
def tiny_gpt_neox_dir(tmp_path_factory):
    from transformers import GPTNeoXConfig, GPTNeoXForCausalLM
    return _build(tmp_path_factory, "tiny-neox", GPTNeoXConfig,
                  GPTNeoXForCausalLM, hidden_size=64, num_hidden_layers=2,
                  num_attention_heads=4, intermediate_size=128,
                  rotary_pct=0.25, max_position_embeddings=128,
                  bos_token_id=1, eos_token_id=1)


@pytest.fixture(scope="session")
def tiny_gptj_dir(tmp_path_factory):
    from transformers import GPTJConfig, GPTJForCausalLM
    return _build(tmp_path_factory, "tiny-gptj", GPTJConfig, GPTJForCausalLM,
                  n_embd=64, n_layer=2, n_head=4, rotary_dim=8,
                  n_positions=128, bos_token_id=1, eos_token_id=1)


@pytest.fixture(scope="session")
def tiny_phi_dir(tmp_path_factory):
    from transformers import PhiConfig, PhiForCausalLM
    return _build(tmp_path_factory, "tiny-phi", PhiConfig, PhiForCausalLM,
                  hidden_size=64, intermediate_size=128, num_hidden_layers=2,
                  num_attention_heads=4, partial_rotary_factor=0.5,
                  max_position_embeddings=128, bos_token_id=1,
                  eos_token_id=1, pad_token_id=0)


@pytest.fixture(scope="session")
def tiny_falcon_new_dir(tmp_path_factory):
    """Falcon 40b-style: new decoder arch, GQA, parallel residual."""
    from transformers import FalconConfig, FalconForCausalLM
    return _build(tmp_path_factory, "tiny-falcon-new", FalconConfig,
                  FalconForCausalLM, hidden_size=64, num_hidden_layers=2,
                  num_attention_heads=4, num_kv_heads=2,
                  new_decoder_architecture=True, bias=False, alibi=False,
                  parallel_attn=True, max_position_embeddings=128,
                  bos_token_id=1, eos_token_id=1)


@pytest.fixture(scope="session")
def tiny_falcon_mq_dir(tmp_path_factory):
    """Falcon 7b-style: multi-query, single shared layernorm."""
    from transformers import FalconConfig, FalconForCausalLM
    return _build(tmp_path_factory, "tiny-falcon-mq", FalconConfig,
                  FalconForCausalLM, hidden_size=64, num_hidden_layers=2,
                  num_attention_heads=4, new_decoder_architecture=False,
                  multi_query=True, parallel_attn=True, bias=False,
                  alibi=False, max_position_embeddings=128,
                  bos_token_id=1, eos_token_id=1)


@pytest.fixture(scope="session")
def tiny_mpt_dir(tmp_path_factory):
    from transformers import MptConfig, MptForCausalLM
    return _build(tmp_path_factory, "tiny-mpt", MptConfig, MptForCausalLM,
                  d_model=64, n_heads=4, n_layers=2, expansion_ratio=4,
                  max_seq_len=128, no_bias=True, eos_token_id=1,
                  bos_token_id=1, pad_token_id=0)


@pytest.fixture(scope="session")
def tiny_gpt_bigcode_dir(tmp_path_factory):
    from transformers import GPTBigCodeConfig, GPTBigCodeForCausalLM
    return _build(tmp_path_factory, "tiny-bigcode", GPTBigCodeConfig,
                  GPTBigCodeForCausalLM, n_embd=64, n_layer=2, n_head=4,
                  n_positions=128, multi_query=True, bos_token_id=1,
                  eos_token_id=1, pad_token_id=0)


@pytest.fixture(scope="session")
def tiny_gpt_bigcode_mha_dir(tmp_path_factory):
    """multi_query=False: c_attn is per-head [q,k,v] interleaved."""
    from transformers import GPTBigCodeConfig, GPTBigCodeForCausalLM
    return _build(tmp_path_factory, "tiny-bigcode-mha", GPTBigCodeConfig,
                  GPTBigCodeForCausalLM, n_embd=64, n_layer=2, n_head=4,
                  n_positions=128, multi_query=False, bos_token_id=1,
                  eos_token_id=1, pad_token_id=0)


@pytest.fixture(scope="session")
def tiny_stablelm_dir(tmp_path_factory):
    from transformers import StableLmConfig, StableLmForCausalLM
    return _build(tmp_path_factory, "tiny-stablelm", StableLmConfig,
                  StableLmForCausalLM, hidden_size=64, intermediate_size=128,
                  num_hidden_layers=2, num_attention_heads=4,
                  num_key_value_heads=2, partial_rotary_factor=0.25,
                  max_position_embeddings=128, use_qkv_bias=True,
                  tie_word_embeddings=False, bos_token_id=1, eos_token_id=1,
                  pad_token_id=0)


def _engine_generate_greedy(model_dir, prompts, max_tokens):
    from intellillm_tpu import LLM, SamplingParams
    llm = LLM(model=model_dir, dtype="float32",
              num_device_blocks_override=128, max_model_len=128,
              max_num_seqs=8, max_paddings=512, swap_space=0.01)
    outputs = llm.generate(prompts,
                           SamplingParams(temperature=0.0,
                                          max_tokens=max_tokens))
    return [o.outputs[0].token_ids for o in outputs]


def _trim_eos(ids, eos=1):
    out = []
    for t in ids:
        out.append(t)
        if t == eos:
            break
    return out


def _check_family(model_dir, example_prompts, hf_runner):
    hf = hf_runner(model_dir)
    hf_out = hf.generate_greedy(example_prompts, MAX_TOKENS)
    ours = _engine_generate_greedy(model_dir, example_prompts, MAX_TOKENS)
    for i, (h, o) in enumerate(zip(hf_out, ours)):
        assert _trim_eos(h) == _trim_eos(o), f"prompt {i}: hf={h} ours={o}"


def test_gpt2_matches_hf(tiny_gpt2_dir, example_prompts, hf_runner):
    _check_family(tiny_gpt2_dir, example_prompts, hf_runner)


def test_qwen2_matches_hf(tiny_qwen2_dir, example_prompts, hf_runner):
    _check_family(tiny_qwen2_dir, example_prompts, hf_runner)


def test_mixtral_matches_hf(tiny_mixtral_dir, example_prompts, hf_runner):
    _check_family(tiny_mixtral_dir, example_prompts, hf_runner)


def test_bloom_matches_hf(tiny_bloom_dir, example_prompts, hf_runner):
    _check_family(tiny_bloom_dir, example_prompts, hf_runner)


def test_gpt_neox_matches_hf(tiny_gpt_neox_dir, example_prompts, hf_runner):
    _check_family(tiny_gpt_neox_dir, example_prompts, hf_runner)


def test_gptj_matches_hf(tiny_gptj_dir, example_prompts, hf_runner):
    _check_family(tiny_gptj_dir, example_prompts, hf_runner)


def test_phi_matches_hf(tiny_phi_dir, example_prompts, hf_runner):
    _check_family(tiny_phi_dir, example_prompts, hf_runner)


def test_falcon_new_arch_matches_hf(tiny_falcon_new_dir, example_prompts,
                                    hf_runner):
    _check_family(tiny_falcon_new_dir, example_prompts, hf_runner)


def test_falcon_multi_query_matches_hf(tiny_falcon_mq_dir, example_prompts,
                                       hf_runner):
    _check_family(tiny_falcon_mq_dir, example_prompts, hf_runner)


def test_mpt_matches_hf(tiny_mpt_dir, example_prompts, hf_runner):
    _check_family(tiny_mpt_dir, example_prompts, hf_runner)


def test_gpt_bigcode_matches_hf(tiny_gpt_bigcode_dir, example_prompts,
                                hf_runner):
    _check_family(tiny_gpt_bigcode_dir, example_prompts, hf_runner)


def test_stablelm_matches_hf(tiny_stablelm_dir, example_prompts, hf_runner):
    _check_family(tiny_stablelm_dir, example_prompts, hf_runner)


def test_gpt_bigcode_mha_matches_hf(tiny_gpt_bigcode_mha_dir,
                                    example_prompts, hf_runner):
    _check_family(tiny_gpt_bigcode_mha_dir, example_prompts, hf_runner)


@pytest.fixture(scope="session")
def tiny_mistral_dir(tmp_path_factory):
    """Sliding window smaller than the generation length, so the ring
    block layout and window mask are actually exercised."""
    from transformers import MistralConfig, MistralForCausalLM
    return _build(tmp_path_factory, "tiny-mistral", MistralConfig,
                  MistralForCausalLM, hidden_size=64, intermediate_size=128,
                  num_hidden_layers=2, num_attention_heads=4,
                  num_key_value_heads=2, sliding_window=32,
                  max_position_embeddings=128, tie_word_embeddings=False,
                  pad_token_id=0, bos_token_id=1, eos_token_id=1,
                  attn_implementation="eager")


def test_mistral_sliding_window_matches_hf(tiny_mistral_dir,
                                           example_prompts, hf_runner):
    """Greedy parity past the sliding window (reference
    tests/models/test_mistral.py role): 40 generated tokens with
    window=32 — the ring KV layout must reproduce HF's windowed mask."""
    hf = hf_runner(tiny_mistral_dir)
    hf_out = hf.generate_greedy(example_prompts, 40)
    ours = _engine_generate_greedy(tiny_mistral_dir, example_prompts, 40)
    for i, (h, o) in enumerate(zip(hf_out, ours)):
        assert _trim_eos(h) == _trim_eos(o), f"prompt {i}: hf={h} ours={o}"


def test_beam_search_deterministic_and_ranked(tiny_opt_dir,
                                              example_prompts):
    """Beam search (best_of=2): returns best_of distinct ranked
    candidates and is deterministic across runs. (No beam-vs-greedy
    logprob assertion: beam maximizes prefix scores stepwise, so the
    final beam score is not guaranteed >= the greedy sequence's.)"""
    from intellillm_tpu import LLM, SamplingParams

    llm = LLM(model=tiny_opt_dir, dtype="float32",
              num_device_blocks_override=128, max_model_len=128,
              max_num_seqs=8, max_paddings=512, swap_space=0.01)
    beam_params = SamplingParams(temperature=0.0, use_beam_search=True,
                                 best_of=2, n=2, max_tokens=8,
                                 ignore_eos=True)
    out1 = llm.generate(example_prompts[:2], beam_params)
    out2 = llm.generate(example_prompts[:2], beam_params)

    for o1, o2 in zip(out1, out2):
        assert len(o1.outputs) == 2
        toks1 = [c.token_ids for c in o1.outputs]
        assert toks1 == [c.token_ids for c in o2.outputs]  # deterministic
        assert toks1[0] != toks1[1]                        # distinct beams
        lps = [c.cumulative_logprob for c in o1.outputs]
        assert lps[0] >= lps[1] - 1e-6                     # ranked
