"""Golden greedy parity vs HF transformers for each model family
(reference pattern: tests/models/test_models.py over 13 families)."""
import pytest
import torch

MAX_TOKENS = 16


def _build(tmp_path_factory, name, config_cls, model_cls, **cfg_kwargs):
    from tests.conftest import _build_word_tokenizer
    d = str(tmp_path_factory.mktemp(name))
    _, vocab_size = _build_word_tokenizer(d)
    torch.manual_seed(0)
    config = config_cls(vocab_size=vocab_size, **cfg_kwargs)
    model = model_cls(config)
    model.eval()
    model.save_pretrained(d, safe_serialization=True)
    return d


@pytest.fixture(scope="session")
def tiny_gpt2_dir(tmp_path_factory):
    from transformers import GPT2Config, GPT2LMHeadModel
    return _build(tmp_path_factory, "tiny-gpt2", GPT2Config, GPT2LMHeadModel,
                  n_embd=64, n_layer=2, n_head=4, n_positions=128,
                  bos_token_id=1, eos_token_id=1)


@pytest.fixture(scope="session")
def tiny_qwen2_dir(tmp_path_factory):
    from transformers import Qwen2Config, Qwen2ForCausalLM
    return _build(tmp_path_factory, "tiny-qwen2", Qwen2Config,
                  Qwen2ForCausalLM, hidden_size=64, intermediate_size=128,
                  num_hidden_layers=2, num_attention_heads=4,
                  num_key_value_heads=2, max_position_embeddings=128,
                  tie_word_embeddings=False, pad_token_id=0, bos_token_id=1,
                  eos_token_id=1)


@pytest.fixture(scope="session")
def tiny_mixtral_dir(tmp_path_factory):
    from transformers import MixtralConfig, MixtralForCausalLM
    return _build(tmp_path_factory, "tiny-mixtral", MixtralConfig,
                  MixtralForCausalLM, hidden_size=64, intermediate_size=96,
                  num_hidden_layers=2, num_attention_heads=4,
                  num_key_value_heads=2, num_local_experts=4,
                  num_experts_per_tok=2, max_position_embeddings=128,
                  tie_word_embeddings=False, pad_token_id=0, bos_token_id=1,
                  eos_token_id=1)


@pytest.fixture(scope="session")
def tiny_bloom_dir(tmp_path_factory):
    from transformers import BloomConfig, BloomForCausalLM
    return _build(tmp_path_factory, "tiny-bloom", BloomConfig,
                  BloomForCausalLM, hidden_size=64, n_layer=2, n_head=4,
                  bos_token_id=1, eos_token_id=1, pad_token_id=0)


@pytest.fixture(scope="session")
def tiny_gpt_neox_dir(tmp_path_factory):
    from transformers import GPTNeoXConfig, GPTNeoXForCausalLM
    return _build(tmp_path_factory, "tiny-neox", GPTNeoXConfig,
                  GPTNeoXForCausalLM, hidden_size=64, num_hidden_layers=2,
                  num_attention_heads=4, intermediate_size=128,
                  rotary_pct=0.25, max_position_embeddings=128,
                  bos_token_id=1, eos_token_id=1)


@pytest.fixture(scope="session")
def tiny_gptj_dir(tmp_path_factory):
    from transformers import GPTJConfig, GPTJForCausalLM
    return _build(tmp_path_factory, "tiny-gptj", GPTJConfig, GPTJForCausalLM,
                  n_embd=64, n_layer=2, n_head=4, rotary_dim=8,
                  n_positions=128, bos_token_id=1, eos_token_id=1)


@pytest.fixture(scope="session")
def tiny_phi_dir(tmp_path_factory):
    from transformers import PhiConfig, PhiForCausalLM
    return _build(tmp_path_factory, "tiny-phi", PhiConfig, PhiForCausalLM,
                  hidden_size=64, intermediate_size=128, num_hidden_layers=2,
                  num_attention_heads=4, partial_rotary_factor=0.5,
                  max_position_embeddings=128, bos_token_id=1,
                  eos_token_id=1, pad_token_id=0)


@pytest.fixture(scope="session")
def tiny_falcon_new_dir(tmp_path_factory):
    """Falcon 40b-style: new decoder arch, GQA, parallel residual."""
    from transformers import FalconConfig, FalconForCausalLM
    return _build(tmp_path_factory, "tiny-falcon-new", FalconConfig,
                  FalconForCausalLM, hidden_size=64, num_hidden_layers=2,
                  num_attention_heads=4, num_kv_heads=2,
                  new_decoder_architecture=True, bias=False, alibi=False,
                  parallel_attn=True, max_position_embeddings=128,
                  bos_token_id=1, eos_token_id=1)


@pytest.fixture(scope="session")
def tiny_falcon_mq_dir(tmp_path_factory):
    """Falcon 7b-style: multi-query, single shared layernorm."""
    from transformers import FalconConfig, FalconForCausalLM
    return _build(tmp_path_factory, "tiny-falcon-mq", FalconConfig,
                  FalconForCausalLM, hidden_size=64, num_hidden_layers=2,
                  num_attention_heads=4, new_decoder_architecture=False,
                  multi_query=True, parallel_attn=True, bias=False,
                  alibi=False, max_position_embeddings=128,
                  bos_token_id=1, eos_token_id=1)


@pytest.fixture(scope="session")
def tiny_mpt_dir(tmp_path_factory):
    from transformers import MptConfig, MptForCausalLM
    return _build(tmp_path_factory, "tiny-mpt", MptConfig, MptForCausalLM,
                  d_model=64, n_heads=4, n_layers=2, expansion_ratio=4,
                  max_seq_len=128, no_bias=True, eos_token_id=1,
                  bos_token_id=1, pad_token_id=0)


def test_mpt_qk_ln(tiny_mpt_dir, tmp_path_factory, example_prompts):
    """llm-foundry qk_ln (full-width LayerNorm on q/k after the Wqkv
    split — reference mpt.py q_ln/k_ln; previously rejected with
    NotImplementedError). HF's MptModel cannot execute such checkpoints,
    so the check is the defining invariance: LayerNorm output is
    scale-invariant in its input, so scaling the q/k slices of Wqkv must
    NOT change outputs when qk_ln is on (it very much does when off)."""
    import json as _json
    import os
    import shutil

    import numpy as np
    import safetensors.numpy

    def variant(name, scale_qk, qk_ln):
        src = tiny_mpt_dir
        d = str(tmp_path_factory.mktemp(name))
        for f in os.listdir(src):
            if f != "model.safetensors":
                shutil.copy(os.path.join(src, f), d)
        sd = safetensors.numpy.load_file(
            os.path.join(src, "model.safetensors"))
        e = 64
        for k in list(sd):
            if k.endswith("attn.Wqkv.weight"):
                w = sd[k].copy()          # [3e, e] torch layout
                w[:2 * e] *= scale_qk
                sd[k] = w
                if qk_ln:
                    prefix = k[:-len("Wqkv.weight")]
                    rng = np.random.default_rng(5)
                    sd[prefix + "q_ln.weight"] = rng.uniform(
                        0.5, 1.5, e).astype(np.float32)
                    sd[prefix + "k_ln.weight"] = rng.uniform(
                        0.5, 1.5, e).astype(np.float32)
        safetensors.numpy.save_file(sd, os.path.join(d,
                                                     "model.safetensors"))
        with open(os.path.join(d, "config.json")) as f:
            cfg = _json.load(f)
        cfg.setdefault("attn_config", {})["qk_ln"] = qk_ln
        with open(os.path.join(d, "config.json"), "w") as f:
            _json.dump(cfg, f)
        return d

    def greedy_with_lp(model_dir):
        from intellillm_tpu import LLM, SamplingParams
        llm = LLM(model=model_dir, dtype="float32",
                  num_device_blocks_override=128, max_model_len=128,
                  max_num_seqs=8, max_paddings=512, swap_space=0.01)
        outs = llm.generate(example_prompts,
                            SamplingParams(temperature=0.0, max_tokens=8))
        return ([o.outputs[0].token_ids for o in outs],
                np.array([o.outputs[0].cumulative_logprob for o in outs]))

    base_ln = variant("mpt-qkln", 1.0, True)
    scaled_ln = variant("mpt-qkln-scaled", 3.0, True)
    plain = variant("mpt-plain", 1.0, False)
    plain_scaled = variant("mpt-plain-scaled", 3.0, False)
    toks_ln, lp_ln = greedy_with_lp(base_ln)
    toks_scaled, lp_scaled = greedy_with_lp(scaled_ln)
    _, lp_plain = greedy_with_lp(plain)
    _, lp_plain_scaled = greedy_with_lp(plain_scaled)
    # With qk_ln, scaling q/k is a no-op down to the logprobs (float32
    # rounding noise only)...
    assert toks_ln == toks_scaled
    np.testing.assert_allclose(lp_ln, lp_scaled, atol=5e-3)
    # ...while without it the same scaling shifts the distribution by
    # orders of magnitude more — proving the invariance comes from the
    # LayerNorm, not from a degenerate model.
    assert np.abs(lp_plain - lp_plain_scaled).max() > 0.1
    # And the norm itself changes the distribution vs no-norm.
    assert np.abs(lp_ln - lp_plain).max() > 0.1


@pytest.fixture(scope="session")
def tiny_gpt_bigcode_dir(tmp_path_factory):
    from transformers import GPTBigCodeConfig, GPTBigCodeForCausalLM
    return _build(tmp_path_factory, "tiny-bigcode", GPTBigCodeConfig,
                  GPTBigCodeForCausalLM, n_embd=64, n_layer=2, n_head=4,
                  n_positions=128, multi_query=True, bos_token_id=1,
                  eos_token_id=1, pad_token_id=0)


@pytest.fixture(scope="session")
def tiny_gpt_bigcode_mha_dir(tmp_path_factory):
    """multi_query=False: c_attn is per-head [q,k,v] interleaved."""
    from transformers import GPTBigCodeConfig, GPTBigCodeForCausalLM
    return _build(tmp_path_factory, "tiny-bigcode-mha", GPTBigCodeConfig,
                  GPTBigCodeForCausalLM, n_embd=64, n_layer=2, n_head=4,
                  n_positions=128, multi_query=False, bos_token_id=1,
                  eos_token_id=1, pad_token_id=0)


@pytest.fixture(scope="session")
def tiny_stablelm_dir(tmp_path_factory):
    from transformers import StableLmConfig, StableLmForCausalLM
    return _build(tmp_path_factory, "tiny-stablelm", StableLmConfig,
                  StableLmForCausalLM, hidden_size=64, intermediate_size=128,
                  num_hidden_layers=2, num_attention_heads=4,
                  num_key_value_heads=2, partial_rotary_factor=0.25,
                  max_position_embeddings=128, use_qkv_bias=True,
                  tie_word_embeddings=False, bos_token_id=1, eos_token_id=1,
                  pad_token_id=0)


def _engine_generate_greedy(model_dir, prompts, max_tokens):
    from intellillm_tpu import LLM, SamplingParams
    llm = LLM(model=model_dir, dtype="float32",
              num_device_blocks_override=128, max_model_len=128,
              max_num_seqs=8, max_paddings=512, swap_space=0.01)
    outputs = llm.generate(prompts,
                           SamplingParams(temperature=0.0,
                                          max_tokens=max_tokens))
    return [o.outputs[0].token_ids for o in outputs]


def _trim_eos(ids, eos=1):
    out = []
    for t in ids:
        out.append(t)
        if t == eos:
            break
    return out


def _check_family(model_dir, example_prompts, hf_runner):
    hf = hf_runner(model_dir)
    hf_out = hf.generate_greedy(example_prompts, MAX_TOKENS)
    ours = _engine_generate_greedy(model_dir, example_prompts, MAX_TOKENS)
    for i, (h, o) in enumerate(zip(hf_out, ours)):
        assert _trim_eos(h) == _trim_eos(o), f"prompt {i}: hf={h} ours={o}"


def test_gpt2_matches_hf(tiny_gpt2_dir, example_prompts, hf_runner):
    _check_family(tiny_gpt2_dir, example_prompts, hf_runner)


def test_qwen2_matches_hf(tiny_qwen2_dir, example_prompts, hf_runner):
    _check_family(tiny_qwen2_dir, example_prompts, hf_runner)


def test_mixtral_matches_hf(tiny_mixtral_dir, example_prompts, hf_runner):
    _check_family(tiny_mixtral_dir, example_prompts, hf_runner)


def test_bloom_matches_hf(tiny_bloom_dir, example_prompts, hf_runner):
    _check_family(tiny_bloom_dir, example_prompts, hf_runner)


def test_gpt_neox_matches_hf(tiny_gpt_neox_dir, example_prompts, hf_runner):
    _check_family(tiny_gpt_neox_dir, example_prompts, hf_runner)


def test_gptj_matches_hf(tiny_gptj_dir, example_prompts, hf_runner):
    _check_family(tiny_gptj_dir, example_prompts, hf_runner)


def test_phi_matches_hf(tiny_phi_dir, example_prompts, hf_runner):
    _check_family(tiny_phi_dir, example_prompts, hf_runner)


def test_falcon_new_arch_matches_hf(tiny_falcon_new_dir, example_prompts,
                                    hf_runner):
    _check_family(tiny_falcon_new_dir, example_prompts, hf_runner)


def test_falcon_multi_query_matches_hf(tiny_falcon_mq_dir, example_prompts,
                                       hf_runner):
    _check_family(tiny_falcon_mq_dir, example_prompts, hf_runner)


def test_mpt_matches_hf(tiny_mpt_dir, example_prompts, hf_runner):
    _check_family(tiny_mpt_dir, example_prompts, hf_runner)


def test_gpt_bigcode_matches_hf(tiny_gpt_bigcode_dir, example_prompts,
                                hf_runner):
    _check_family(tiny_gpt_bigcode_dir, example_prompts, hf_runner)


def test_stablelm_matches_hf(tiny_stablelm_dir, example_prompts, hf_runner):
    _check_family(tiny_stablelm_dir, example_prompts, hf_runner)


@pytest.fixture(scope="session")
def tiny_stablelm2_dir(tmp_path_factory):
    """StableLM-2 shape: per-head qk layernorms + parallel residual
    (stablelm-2-1_6b / -zephyr configs set both). transformers'
    _init_weights assumes every LayerNorm has a bias, but the per-head
    norms are bias-free — shield the init for the tiny random build."""
    from tests.conftest import _build_word_tokenizer
    from transformers import StableLmConfig, StableLmForCausalLM
    from transformers.models.stablelm import modeling_stablelm as ms

    d = str(tmp_path_factory.mktemp("tiny-stablelm2"))
    _, vocab_size = _build_word_tokenizer(d)
    torch.manual_seed(0)
    config = StableLmConfig(
        vocab_size=vocab_size, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        partial_rotary_factor=0.25, max_position_embeddings=128,
        use_qkv_bias=True, qk_layernorm=True, use_parallel_residual=True,
        tie_word_embeddings=False, bos_token_id=1, eos_token_id=1,
        pad_token_id=0)
    orig = ms.StableLmPreTrainedModel._init_weights

    def safe_init(self, module):
        try:
            orig(self, module)
        except AttributeError:
            if getattr(module, "weight", None) is not None:
                module.weight.data.fill_(1.0)

    ms.StableLmPreTrainedModel._init_weights = safe_init
    try:
        model = StableLmForCausalLM(config)
    finally:
        ms.StableLmPreTrainedModel._init_weights = orig
    # Give the per-head norms non-trivial weights so the golden actually
    # exercises them.
    with torch.no_grad():
        for layer in model.model.layers:
            for ln in (list(layer.self_attn.q_layernorm.norms)
                       + list(layer.self_attn.k_layernorm.norms)):
                ln.weight.uniform_(0.5, 1.5)
    model.eval()
    model.save_pretrained(d, safe_serialization=True)
    return d


def test_stablelm_qkln_parallel_residual_matches_hf(tiny_stablelm2_dir,
                                                    example_prompts,
                                                    hf_runner):
    """qk_layernorm + use_parallel_residual (previously rejected with
    NotImplementedError — VERDICT r4 listed them as real gaps)."""
    _check_family(tiny_stablelm2_dir, example_prompts, hf_runner)


def test_gpt_bigcode_mha_matches_hf(tiny_gpt_bigcode_mha_dir,
                                    example_prompts, hf_runner):
    _check_family(tiny_gpt_bigcode_mha_dir, example_prompts, hf_runner)


@pytest.fixture(scope="session")
def tiny_mistral_dir(tmp_path_factory):
    """Sliding window smaller than the generation length, so the ring
    block layout and window mask are actually exercised."""
    from transformers import MistralConfig, MistralForCausalLM
    return _build(tmp_path_factory, "tiny-mistral", MistralConfig,
                  MistralForCausalLM, hidden_size=64, intermediate_size=128,
                  num_hidden_layers=2, num_attention_heads=4,
                  num_key_value_heads=2, sliding_window=32,
                  max_position_embeddings=128, tie_word_embeddings=False,
                  pad_token_id=0, bos_token_id=1, eos_token_id=1,
                  attn_implementation="eager")


def test_mistral_sliding_window_matches_hf(tiny_mistral_dir,
                                           example_prompts, hf_runner):
    """Greedy parity past the sliding window (reference
    tests/models/test_mistral.py role): 40 generated tokens with
    window=32 — the ring KV layout must reproduce HF's windowed mask."""
    hf = hf_runner(tiny_mistral_dir)
    hf_out = hf.generate_greedy(example_prompts, 40)
    ours = _engine_generate_greedy(tiny_mistral_dir, example_prompts, 40)
    for i, (h, o) in enumerate(zip(hf_out, ours)):
        assert _trim_eos(h) == _trim_eos(o), f"prompt {i}: hf={h} ours={o}"


def test_beam_search_deterministic_and_ranked(tiny_opt_dir,
                                              example_prompts):
    """Beam search (best_of=2): returns best_of distinct ranked
    candidates and is deterministic across runs. (No beam-vs-greedy
    logprob assertion: beam maximizes prefix scores stepwise, so the
    final beam score is not guaranteed >= the greedy sequence's.)"""
    from intellillm_tpu import LLM, SamplingParams

    llm = LLM(model=tiny_opt_dir, dtype="float32",
              num_device_blocks_override=128, max_model_len=128,
              max_num_seqs=8, max_paddings=512, swap_space=0.01)
    beam_params = SamplingParams(temperature=0.0, use_beam_search=True,
                                 best_of=2, n=2, max_tokens=8,
                                 ignore_eos=True)
    out1 = llm.generate(example_prompts[:2], beam_params)
    out2 = llm.generate(example_prompts[:2], beam_params)

    for o1, o2 in zip(out1, out2):
        assert len(o1.outputs) == 2
        toks1 = [c.token_ids for c in o1.outputs]
        assert toks1 == [c.token_ids for c in o2.outputs]  # deterministic
        assert toks1[0] != toks1[1]                        # distinct beams
        lps = [c.cumulative_logprob for c in o1.outputs]
        assert lps[0] >= lps[1] - 1e-6                     # ranked
