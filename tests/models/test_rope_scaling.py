"""RoPE scaling variants: golden greedy parity vs HF transformers.

Role parity: reference `vllm/model_executor/layers/rotary_embedding.py`
(LinearScaling :151, DynamicNTKScaling :187, YaRN :268) — previously
covered only by default-rope family goldens (VERDICT r4 weak #3).

`linear` and `yarn` compare end-to-end against HF llama (transformers
implements the same table construction). `dynamic` CANNOT golden against
HF: transformers recomputes the NTK base per forward from the live
sequence length, while this repo (like the reference, which must serve
from a fixed precomputed table) scales once for the full extended
context — for prompts short of the original window the two legitimately
differ. Dynamic is instead checked against the reference's closed-form
table formula.
"""
import numpy as np
import pytest
import torch

MAX_TOKENS = 16


def _build_rope_llama(tmp_path_factory, name, rope_scaling,
                      max_position_embeddings=128):
    from tests.conftest import _build_word_tokenizer
    from transformers import LlamaConfig, LlamaForCausalLM

    d = str(tmp_path_factory.mktemp(name))
    _, vocab_size = _build_word_tokenizer(d)
    torch.manual_seed(0)
    config = LlamaConfig(
        vocab_size=vocab_size, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=max_position_embeddings,
        rope_scaling=rope_scaling, tie_word_embeddings=False,
        pad_token_id=0, bos_token_id=1, eos_token_id=1,
        torch_dtype=torch.float32)
    model = LlamaForCausalLM(config)
    model.eval()
    model.save_pretrained(d, safe_serialization=True)
    return d


def _engine_greedy(model_dir, prompts, max_tokens, max_model_len=128):
    from intellillm_tpu import LLM, SamplingParams
    llm = LLM(model=model_dir, dtype="float32",
              num_device_blocks_override=128, max_model_len=max_model_len,
              max_num_seqs=8, max_paddings=512, swap_space=0.01)
    outs = llm.generate(prompts, SamplingParams(temperature=0.0,
                                                max_tokens=max_tokens))
    return [o.outputs[0].token_ids for o in outs]


def _trim_eos(ids, eos=1):
    out = []
    for t in ids:
        out.append(t)
        if t == eos:
            break
    return out


@pytest.mark.parametrize("rope_scaling,mml", [
    ({"rope_type": "linear", "factor": 2.0}, 128),
    ({"rope_type": "yarn", "factor": 2.0,
      "original_max_position_embeddings": 64}, 128),
    ({"rope_type": "yarn", "factor": 4.0, "beta_fast": 16, "beta_slow": 2,
      "original_max_position_embeddings": 32}, 128),
], ids=["linear", "yarn", "yarn-betas"])
def test_rope_scaling_matches_hf(tmp_path_factory, example_prompts,
                                 hf_runner, rope_scaling, mml):
    base_mpe = rope_scaling.get("original_max_position_embeddings", 64)
    d = _build_rope_llama(
        tmp_path_factory,
        f"tiny-llama-{rope_scaling['rope_type']}", rope_scaling,
        max_position_embeddings=base_mpe)
    hf = hf_runner(d)
    hf_out = hf.generate_greedy(example_prompts, MAX_TOKENS)
    ours = _engine_greedy(d, example_prompts, MAX_TOKENS,
                          max_model_len=mml)
    for i, (h, o) in enumerate(zip(hf_out, ours)):
        assert _trim_eos(h) == _trim_eos(o), f"prompt {i}: hf={h} ours={o}"


def test_dynamic_ntk_matches_reference_formula():
    """dynamic: table equals the reference's closed form
    (rotary_embedding.py:187-210 — adjusted base over the extended
    length), and get_rope routes {"type": "dynamic"} to it."""
    from intellillm_tpu.layers.rotary_embedding import get_rope

    head, rd, mpe, base, factor = 16, 16, 64, 10000.0, 4.0
    rope = get_rope(head, rd, mpe, base,
                    rope_scaling={"type": "dynamic", "factor": factor})
    max_len = int(mpe * factor)
    adj_base = base * ((factor * max_len / mpe) -
                       (factor - 1)) ** (rd / (rd - 2))
    inv = 1.0 / (adj_base ** (np.arange(0, rd, 2, dtype=np.float64) / rd))
    t = np.arange(max_len, dtype=np.float64)
    freqs = np.outer(t, inv)
    np.testing.assert_allclose(np.asarray(rope.cos_cache),
                               np.cos(freqs).astype(np.float32),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(rope.sin_cache),
                               np.sin(freqs).astype(np.float32),
                               rtol=1e-6, atol=1e-6)
    assert rope.cos_cache.shape[0] == max_len


def test_dynamic_ntk_e2e_past_original_window(tmp_path_factory):
    """dynamic e2e smoke: a model whose original window is 64 loads with
    the scaled table and generates greedily past position 64 without
    error (the scaled rope actually engaged: the model's rope table is
    the adjusted-base one, not the default)."""
    from intellillm_tpu.layers.rotary_embedding import (
        DynamicNTKScalingRotaryEmbedding, _ROPE_CACHE)

    d_dyn = _build_rope_llama(
        tmp_path_factory, "tiny-llama-dynamic",
        {"rope_type": "dynamic", "factor": 2.0},
        max_position_embeddings=64)
    long_prompt = " ".join(["the cat runs fast and the dog"] * 10)
    dyn = _engine_greedy(d_dyn, [long_prompt], 24, max_model_len=128)
    assert len(dyn[0]) == 24
    assert any(isinstance(r, DynamicNTKScalingRotaryEmbedding)
               and r.cos_cache.shape[0] == 128
               for r in _ROPE_CACHE.values())
