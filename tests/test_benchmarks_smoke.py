"""Benchmark-harness smoke tests: the latency/throughput CLIs must run
end to end on a tiny dummy engine (CPU via INTELLILLM_JAX_PLATFORM)."""
import os
import subprocess
import sys

import pytest


def _run(args):
    env = dict(os.environ)
    env["INTELLILLM_JAX_PLATFORM"] = "cpu"
    return subprocess.run([sys.executable] + args, env=env,
                          capture_output=True, text=True, timeout=420)


def test_benchmark_latency_smoke():
    r = _run(["benchmarks/benchmark_latency.py", "--model", "dummy:tiny",
              "--input-len", "8", "--output-len", "8", "--batch-size", "2",
              "--num-iters", "1", "--num-iters-warmup", "1",
              "--max-model-len", "64", "--num-device-blocks", "64"])
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "Avg latency" in r.stdout


def test_benchmark_throughput_smoke():
    r = _run(["benchmarks/benchmark_throughput.py", "--model", "dummy:tiny",
              "--num-prompts", "4", "--input-len", "8", "--output-len", "8",
              "--max-model-len", "64", "--num-device-blocks", "64",
              "--no-tqdm"])
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "Throughput:" in r.stdout
