"""Benchmark-harness smoke tests: the latency/throughput CLIs must run
end to end on a tiny dummy engine (CPU via INTELLILLM_JAX_PLATFORM)."""
import os
import subprocess
import sys

import pytest


def _run(args):
    env = dict(os.environ)
    env["INTELLILLM_JAX_PLATFORM"] = "cpu"
    return subprocess.run([sys.executable] + args, env=env,
                          capture_output=True, text=True, timeout=420)


def test_benchmark_latency_smoke():
    r = _run(["benchmarks/benchmark_latency.py", "--model", "dummy:tiny",
              "--input-len", "8", "--output-len", "8", "--batch-size", "2",
              "--num-iters", "1", "--num-iters-warmup", "1",
              "--max-model-len", "64", "--num-device-blocks", "64"])
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "Avg latency" in r.stdout


def test_benchmark_throughput_smoke():
    r = _run(["benchmarks/benchmark_throughput.py", "--model", "dummy:tiny",
              "--num-prompts", "4", "--input-len", "8", "--output-len", "8",
              "--max-model-len", "64", "--num-device-blocks", "64",
              "--no-tqdm"])
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "Throughput:" in r.stdout


def test_benchmark_serving_smoke():
    """serve_bench boots the OpenAI server (subprocess, dummy tiny model)
    and drives benchmark_serving's Poisson load generator through real
    HTTP — the whole north-star measurement path, minus the chip."""
    import json
    r = _run(["benchmarks/serve_bench.py", "--size", "tiny",
              "--num-prompts", "4", "--rates", "inf", "--input-len", "8",
              "--output-len", "8", "--max-model-len", "64",
              "--max-num-seqs", "4", "--num-decode-steps", "4",
              "--num-device-blocks", "64", "--port", "8733",
              "--init-timeout", "240"])
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    summary = None
    for line in r.stdout.splitlines():
        if line.startswith('{"serve_bench_summary"'):
            summary = json.loads(line)["serve_bench_summary"]
    assert summary is not None, r.stdout[-2000:]
    (m,) = summary["results"]
    assert m["completed"] == 4
    assert m["output_tok_s"] > 0
    assert m["ttft_percentiles_ms"]["p50"] > 0


def test_serve_bench_fleet_args_parse():
    """The fleet scenario's CLI surface stays wired (cheap guard; the
    full fleet boot lives in the slow smoke below)."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from benchmarks.serve_bench import make_arg_parser
    args = make_arg_parser().parse_args(
        ["--scenario", "fleet", "--num-replicas", "3",
         "--replica-base-port", "9000"])
    assert args.scenario == "fleet"
    assert args.num_replicas == 3
    assert args.replica_base_port == 9000


@pytest.mark.slow
def test_serve_bench_fleet_smoke():
    """Fleet scenario end to end: 2 demo-server replicas behind the
    router, one rate through the router, per-replica SLO split + routing
    counters in the output."""
    import json
    r = _run(["benchmarks/serve_bench.py", "--size", "tiny",
              "--scenario", "fleet", "--num-replicas", "2",
              "--num-prompts", "4", "--rates", "inf", "--input-len", "8",
              "--output-len", "8", "--max-model-len", "64",
              "--max-num-seqs", "4", "--num-decode-steps", "4",
              "--num-device-blocks", "64", "--port", "8735",
              "--replica-base-port", "8741", "--init-timeout", "240",
              "--server-log", "/tmp/serve_bench_fleet.log"])
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    summary = None
    for line in r.stdout.splitlines():
        if line.startswith('{"serve_bench_summary"'):
            summary = json.loads(line)["serve_bench_summary"]
    assert summary is not None, r.stdout[-2000:]
    assert summary["scenario"] == "fleet"
    (m,) = summary["results"]
    assert m["completed"] == 4
    assert m["output_tok_s"] > 0
    per_replica = summary["per_replica_slo"]
    assert set(per_replica) == {"replica-0", "replica-1"}
    assert all("slo" in v for v in per_replica.values())
    router = summary["router"]["metrics"]
    # Warm-up (2x4) + measured (4) requests all went through the router.
    assert sum(router["requests_total"].values()) >= 12
    assert sum(router["decisions"].values()) >= 12
    assert all(v == 1.0 for v in router["replica_healthy"].values())
    # Fleet-aggregated alert state from the router's /debug/alerts: the
    # bench asserts "no page fired" the same way an operator would.
    alerts = summary["alerts"]
    assert alerts["fleet_aggregated"] is True
    assert alerts["page_firing"] is False


def test_sp_prefill_bench_smoke():
    """sp_prefill_bench emits one JSON line per (mode, length) on the CPU
    backend (flash under interpret mode, ring on the virtual mesh)."""
    import json
    from jax.experimental.pallas import tpu as pltpu
    if not hasattr(pltpu, "force_tpu_interpret_mode"):
        pytest.skip("jax.experimental.pallas.tpu lacks "
                    "force_tpu_interpret_mode (older jax); the flash mode "
                    "of sp_prefill_bench cannot run on CPU without it")
    env = dict(os.environ)
    env["INTELLILLM_JAX_PLATFORM"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                        " --xla_force_host_platform_device_count=8")
    r = subprocess.run(
        [sys.executable, "benchmarks/sp_prefill_bench.py", "--size",
         "tiny", "--lengths", "256", "--modes", "flash,ring"],
        env=env, capture_output=True, text=True, timeout=420)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    lines = [json.loads(x) for x in r.stdout.splitlines()
             if x.startswith("{")]
    assert len(lines) == 2
    assert all(x["value"] > 0 for x in lines)


def test_spec_bench_modes_build():
    """spec_bench's engine configuration (draft + force-accept env)
    drives bench.py end to end on CPU."""
    import json
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env.update(INTELLILLM_BENCH_SIZE="tiny", INTELLILLM_BENCH_SPEC="tiny",
               INTELLILLM_BENCH_SPEC_K="2", INTELLILLM_BENCH_BS="2",
               INTELLILLM_BENCH_IN="8", INTELLILLM_BENCH_OUT="4",
               INTELLILLM_SPEC_FORCE_ACCEPT="1")
    r = subprocess.run([sys.executable, "bench.py"], env=env,
                       capture_output=True, text=True, timeout=420)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    line = json.loads(r.stdout.strip().splitlines()[-1])
    assert line["value"] > 0


def test_serve_bench_multi_tenant_args_parse():
    """The multi-tenant scenario's CLI surface stays wired."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from benchmarks.serve_bench import make_arg_parser
    args = make_arg_parser().parse_args(
        ["--scenario", "multi-tenant", "--num-tenants", "4",
         "--hog-concurrency", "12", "--tenant-hog-share-cap", "0.3",
         "--hog-start-delay", "0.5"])
    assert args.scenario == "multi-tenant"
    assert args.num_tenants == 4
    assert args.hog_concurrency == 12
    assert args.tenant_hog_share_cap == 0.3
    assert args.hog_start_delay == 0.5


@pytest.mark.slow
def test_serve_bench_multi_tenant_smoke():
    """Multi-tenant scenario end to end (docs/multitenancy.md): 3 LoRA
    tenants on one tiny replica, hot-loaded adapters, a small hog, and
    the per-tenant SLO split + isolation block in the summary. Tiny
    sizes — this smoke proves the wiring, not the 2x isolation bound
    (that's the full CPU acceptance run's job)."""
    import json
    r = _run(["benchmarks/serve_bench.py", "--size", "tiny",
              "--scenario", "multi-tenant", "--num-tenants", "3",
              "--hog-concurrency", "4", "--hog-output-len", "24",
              "--hog-start-delay", "0.2",
              "--victim-requests", "2", "--victim-output-len", "8",
              "--input-len", "8", "--max-model-len", "64",
              "--max-num-seqs", "4", "--num-decode-steps", "4",
              "--num-device-blocks", "128", "--port", "8737",
              "--init-timeout", "240",
              "--server-log", "/tmp/serve_bench_mt.log"])
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-3000:]
    summary = None
    for line in r.stdout.splitlines():
        if line.startswith('{"serve_bench_summary"'):
            summary = json.loads(line)["serve_bench_summary"]
    assert summary is not None, r.stdout[-2000:]
    assert summary["scenario"] == "multi-tenant"
    assert summary["num_tenants"] == 3
    assert summary["hog"] == "tenant-1"
    phases = summary["victim_latency"]
    assert set(phases) == {"victim_solo", "contention_caps_on",
                           "contention_caps_off"}
    for phase in phases.values():
        assert phase["tpot_ms"]["n"] > 0
        assert phase["tpot_ms"]["p99"] is not None
        # Per-tenant SLO split: both victim tenants measured.
        assert set(phase["per_tenant_tpot_ms"]) == {"tenant-2", "tenant-3"}
    iso = summary["isolation"]
    assert set(iso["victim_tpot_p99_ms"]) == {"solo", "caps_on", "caps_off"}
    assert all(v is not None for v in iso["victim_tpot_p99_ms"].values())
    # Adapter churn counters from the caps-on run's /health/detail.
    churn = iso["adapter_churn"]
    assert set(churn) == {"tenant-1", "tenant-2", "tenant-3"}
    assert sum(c["loads"] or 0 for c in churn.values()) >= 3
    # Per-tenant stats block made it into the snapshot.
    stats = (summary["tenants_caps_on"] or {}).get("stats") or {}
    assert any(t.startswith("tenant-") for t in stats)


def test_serve_bench_replay_args_parse():
    """The replay/diurnal CLI surface stays wired (cheap guard; the
    full capture->replay roundtrip lives in the slow smoke below)."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from benchmarks.serve_bench import make_arg_parser
    args = make_arg_parser().parse_args(
        ["--scenario", "replay", "--workload", "/tmp/w.iwl.jsonl",
         "--speed", "2.0", "--replay-repeat", "2",
         "--summary-out", "/tmp/s.json"])
    assert args.scenario == "replay"
    assert args.workload == "/tmp/w.iwl.jsonl"
    assert args.speed == 2.0
    assert args.replay_repeat == 2
    args = make_arg_parser().parse_args(
        ["--scenario", "diurnal", "--emit-only", "--seed", "7",
         "--diurnal-duration", "5", "--diurnal-bursts", "3",
         "--workload-out", "/tmp/d.iwl.jsonl"])
    assert args.scenario == "diurnal"
    assert args.emit_only and args.diurnal_bursts == 3


def test_diurnal_synth_is_seed_deterministic():
    """Same --seed => byte-identical synthesized workload (the property
    the replay determinism check stands on), different seed => a
    different stream. In-process: no tokenizer, no server."""
    import argparse
    import json as json_mod

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from benchmarks.serve_bench import synth_diurnal
    from intellillm_tpu.obs.workload import dump_iwl, parse_iwl

    def make_args(seed):
        return argparse.Namespace(
            seed=seed, num_prompts=32, diurnal_duration=10.0,
            diurnal_bursts=2, num_tenants=4, input_len=64,
            output_len=32, max_model_len=512)

    a = synth_diurnal(make_args(11))
    b = synth_diurnal(make_args(11))
    assert json_mod.dumps(a) == json_mod.dumps(b)
    c = synth_diurnal(make_args(12))
    assert json_mod.dumps(a) != json_mod.dumps(c)
    assert len(a) == 32
    # Arrivals sorted, lengths inside the context window, adapters churn.
    ts = [r["ts"] for r in a]
    assert ts == sorted(ts) and ts[-1] <= 10.0
    assert all(r["prompt_len"] + r["sampling"]["max_tokens"]
               < 512 for r in a)
    assert len({r["adapter"] for r in a}) > 1
    # The emitted document round-trips as IWL1.
    header, recs = parse_iwl(dump_iwl(a, source="diurnal"))
    assert header["requests"] == 32
    assert [r["id"] for r in recs] == [r["id"] for r in a]


@pytest.mark.slow
def test_serve_bench_replay_roundtrip_smoke():
    """The acceptance path end to end on CPU: synthesize a diurnal
    workload, replay it twice against one booted server, and require
    bit-identical server-side re-captures (replay_deterministic), then
    gate the summary through wdiff against itself (exit 0)."""
    import json
    import tempfile

    out_dir = tempfile.mkdtemp(prefix="replay-smoke-")
    summary_path = os.path.join(out_dir, "summary.json")
    r = _run(["benchmarks/serve_bench.py", "--size", "tiny",
              "--scenario", "diurnal", "--num-prompts", "6",
              "--input-len", "8", "--output-len", "8",
              "--diurnal-duration", "2", "--diurnal-bursts", "1",
              "--max-model-len", "64", "--max-num-seqs", "4",
              "--num-decode-steps", "4", "--num-device-blocks", "64",
              "--replay-repeat", "2", "--seed", "5", "--port", "8735",
              "--init-timeout", "240",
              "--workload-out", os.path.join(out_dir, "d.iwl.jsonl"),
              "--summary-out", summary_path])
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    summary = json.load(open(summary_path))
    assert summary["scenario"] == "replay"
    assert summary["num_requests"] == 6
    assert summary["replay_deterministic"] is True
    assert len(set(summary["recapture_digests"])) == 1
    assert all(m["completed"] == 6 for m in summary["results"])
    assert all(m["recapture"]["count"] == 6 for m in summary["results"])
    # wdiff gates on the snapshot: identical inputs must pass (exit 0).
    w = _run(["-m", "intellillm_tpu.tools.wdiff", summary_path,
              summary_path])
    assert w.returncode == 0, w.stdout + w.stderr
    assert "PASS" in w.stdout
