"""Benchmark-harness smoke tests: the latency/throughput CLIs must run
end to end on a tiny dummy engine (CPU via INTELLILLM_JAX_PLATFORM)."""
import os
import subprocess
import sys

import pytest


def _run(args):
    env = dict(os.environ)
    env["INTELLILLM_JAX_PLATFORM"] = "cpu"
    return subprocess.run([sys.executable] + args, env=env,
                          capture_output=True, text=True, timeout=420)


def test_benchmark_latency_smoke():
    r = _run(["benchmarks/benchmark_latency.py", "--model", "dummy:tiny",
              "--input-len", "8", "--output-len", "8", "--batch-size", "2",
              "--num-iters", "1", "--num-iters-warmup", "1",
              "--max-model-len", "64", "--num-device-blocks", "64"])
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "Avg latency" in r.stdout


def test_benchmark_throughput_smoke():
    r = _run(["benchmarks/benchmark_throughput.py", "--model", "dummy:tiny",
              "--num-prompts", "4", "--input-len", "8", "--output-len", "8",
              "--max-model-len", "64", "--num-device-blocks", "64",
              "--no-tqdm"])
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "Throughput:" in r.stdout


def test_benchmark_serving_smoke():
    """serve_bench boots the OpenAI server (subprocess, dummy tiny model)
    and drives benchmark_serving's Poisson load generator through real
    HTTP — the whole north-star measurement path, minus the chip."""
    import json
    r = _run(["benchmarks/serve_bench.py", "--size", "tiny",
              "--num-prompts", "4", "--rates", "inf", "--input-len", "8",
              "--output-len", "8", "--max-model-len", "64",
              "--max-num-seqs", "4", "--num-decode-steps", "4",
              "--num-device-blocks", "64", "--port", "8733",
              "--init-timeout", "240"])
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    summary = None
    for line in r.stdout.splitlines():
        if line.startswith('{"serve_bench_summary"'):
            summary = json.loads(line)["serve_bench_summary"]
    assert summary is not None, r.stdout[-2000:]
    (m,) = summary["results"]
    assert m["completed"] == 4
    assert m["output_tok_s"] > 0
    assert m["ttft_percentiles_ms"]["p50"] > 0
