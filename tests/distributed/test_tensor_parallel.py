"""Multi-device golden-parity tests on the 8-virtual-CPU mesh.

Reference pattern: `tests/distributed/test_comm_ops.py:19` +
`vllm/test_utils.py:8-37` (real 2-GPU NCCL tests). TPU equivalent: the
same engine driven over a `jax.sharding.Mesh` of 8 virtual CPU devices
(provisioned in tests/conftest.py), asserting exact greedy-token equality
with single-device runs and with HF transformers.
"""
import jax
import pytest

from intellillm_tpu import LLM, SamplingParams
from tests.conftest import EXAMPLE_PROMPTS

MAX_TOKENS = 16


def _generate_greedy(model_dir, prompts, max_tokens, tp=1, dp=1):
    llm = LLM(model=model_dir,
              dtype="float32",
              tensor_parallel_size=tp,
              data_parallel_size=dp,
              num_device_blocks_override=128,
              max_model_len=128,
              max_num_seqs=8,
              max_paddings=512,
              swap_space=0.01)
    params = SamplingParams(temperature=0.0, max_tokens=max_tokens)
    outputs = llm.generate(prompts, params)
    return [o.outputs[0].token_ids for o in outputs], llm


requires_8_devices = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices")


@pytest.fixture(scope="module")
def single_device_reference(tiny_llama_dir):
    """tp=1 greedy tokens, computed once for the whole module."""
    ref, _ = _generate_greedy(tiny_llama_dir, list(EXAMPLE_PROMPTS),
                              MAX_TOKENS)
    return ref


@requires_8_devices
@pytest.mark.parametrize("tp", [2, 4])
def test_tp_greedy_matches_single_device(tiny_llama_dir, example_prompts,
                                         single_device_reference, tp):
    """TP-sharded run must produce the exact same greedy tokens as tp=1."""
    got, _ = _generate_greedy(tiny_llama_dir, example_prompts, MAX_TOKENS,
                              tp=tp)
    for i, (r, g) in enumerate(zip(single_device_reference, got)):
        assert r == g, f"prompt {i} tp={tp}: ref={r} got={g}"


@requires_8_devices
def test_dp2_tp4_greedy_matches_single_device(tiny_llama_dir,
                                              example_prompts,
                                              single_device_reference):
    got, _ = _generate_greedy(tiny_llama_dir, example_prompts, MAX_TOKENS,
                              tp=4, dp=2)
    for i, (r, g) in enumerate(zip(single_device_reference, got)):
        assert r == g, f"prompt {i} dp2xtp4: ref={r} got={g}"


@requires_8_devices
def test_tp_chunked_fused_decode_matches_single_device(
        tiny_llama_dir, example_prompts, single_device_reference,
        monkeypatch):
    """Chunked fused decode (K=32 as four C=8 chunk scans + page commits)
    over a TP=2 mesh must reproduce the single-device tokens — covers
    the per-chunk pool commit and pool-context advance under GSPMD
    sharding of the KV pool."""
    monkeypatch.setenv("INTELLILLM_DECODE_CHUNK", "8")
    llm = LLM(model=tiny_llama_dir, dtype="float32",
              tensor_parallel_size=2, num_device_blocks_override=128,
              max_model_len=128, max_num_seqs=8, max_paddings=512,
              swap_space=0.01, num_decode_steps=32)
    params = SamplingParams(temperature=0.0, max_tokens=MAX_TOKENS)
    outputs = llm.generate(example_prompts, params)
    got = [o.outputs[0].token_ids for o in outputs]
    for i, (r, g) in enumerate(zip(single_device_reference, got)):
        assert r == g, f"prompt {i} tp=2 chunked: ref={r} got={g}"


@requires_8_devices
def test_tp_greedy_matches_hf(tiny_llama_dir, example_prompts, hf_runner):
    """TP=2 run matches HF transformers greedy decode token-for-token."""
    hf = hf_runner(tiny_llama_dir)
    hf_out = hf.generate_greedy(example_prompts, MAX_TOKENS)
    got, _ = _generate_greedy(tiny_llama_dir, example_prompts, MAX_TOKENS,
                              tp=2)

    def trim(ids, eos=1):
        out = []
        for t in ids:
            out.append(t)
            if t == eos:
                break
        return out

    for i, (h, g) in enumerate(zip(hf_out, got)):
        assert trim(h) == trim(g), f"prompt {i}: hf={h} got={g}"


@requires_8_devices
def test_params_and_cache_actually_sharded(tiny_llama_dir, example_prompts):
    """Assert TP actually shards: at least the large matmul params and the
    KV pool must have per-device shards smaller than the global shape
    (i.e. sharding is not silent replication)."""
    _, llm = _generate_greedy(tiny_llama_dir, example_prompts[:1],
                              4, tp=4)
    worker = llm.llm_engine.worker
    mesh = worker.mesh
    assert dict(mesh.shape) == {"data": 1, "model": 4}

    sharded = 0
    total = 0
    for leaf in jax.tree.leaves(worker.params):
        total += 1
        shard_shape = leaf.sharding.shard_shape(leaf.shape)
        if shard_shape != leaf.shape:
            sharded += 1
    # The bulk of params (qkv/o/mlp/embed) must be sharded; small vectors
    # (norms, biases) replicate.
    assert sharded >= total // 3, (
        f"only {sharded}/{total} params sharded under tp=4")

    # KV pool: [blocks, kv_heads=2, block, head] — kv_heads=2 does not
    # divide tp=4, so it legitimately replicates for this tiny model; use
    # a kv-divisible check on the sharding helper directly instead.
    from jax.sharding import PartitionSpec as P
    from intellillm_tpu.parallel.mesh import shard_kv_cache
    kv_sh = shard_kv_cache(mesh)
    assert kv_sh is not None and kv_sh.spec == P(None, "model", None, None)


@requires_8_devices
def test_kv_pool_sharded_when_divisible(tiny_llama_dir):
    """With tp=2 the tiny model's 2 kv heads divide the axis: the pool
    must physically shard by kv head."""
    llm = LLM(model=tiny_llama_dir,
              dtype="float32",
              tensor_parallel_size=2,
              num_device_blocks_override=64,
              max_model_len=128,
              max_num_seqs=4,
              max_paddings=512,
              swap_space=0.01)
    cache = llm.llm_engine.worker.cache_engine.device_cache
    k0, _ = cache[0]
    shard_shape = k0.sharding.shard_shape(k0.shape)
    assert shard_shape[1] == k0.shape[1] // 2, (
        f"kv pool not sharded by head: global={k0.shape} "
        f"shard={shard_shape}")


@requires_8_devices
@pytest.mark.parametrize("tp", [2, 4])
def test_awq_tp_runs_and_matches_tp1(tmp_path_factory, example_prompts, tp):
    """AWQ int4 params shard over TP (s4/z4 replicate the group dim) and
    produce the same greedy tokens as the tp=1 AWQ run."""
    import sys
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM
    from tests.conftest import _build_word_tokenizer
    sys.path.insert(0, "tests")
    from tests.kernels.test_quant_checkpoints import _awqify_checkpoint

    base = str(tmp_path_factory.mktemp("awq-tp") / "base")
    _, vocab_size = _build_word_tokenizer(base)
    torch.manual_seed(0)
    LlamaForCausalLM(LlamaConfig(
        vocab_size=vocab_size, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        max_position_embeddings=128, pad_token_id=0, bos_token_id=1,
        eos_token_id=1, tie_word_embeddings=False,
        torch_dtype=torch.float32)).eval().save_pretrained(
            base, safe_serialization=True)
    awq_dir, _ = _awqify_checkpoint(base, base + "-ck", group=16)

    ref, _ = _generate_greedy(awq_dir, example_prompts, 8)
    got, _ = _generate_greedy(awq_dir, example_prompts, 8, tp=tp)
    assert got == ref


def _dummy_llama_engine(vocab, tp):
    from transformers import LlamaConfig
    from intellillm_tpu.config import (CacheConfig, ModelConfig,
                                       ParallelConfig, SchedulerConfig)
    from intellillm_tpu.engine.llm_engine import LLMEngine

    hf = LlamaConfig(vocab_size=vocab, hidden_size=64,
                     intermediate_size=128, num_hidden_layers=2,
                     num_attention_heads=4, num_key_value_heads=4,
                     max_position_embeddings=128, tie_word_embeddings=False)
    model_config = ModelConfig.from_hf_config(hf, dtype="float32",
                                              max_model_len=128,
                                              load_format="dummy")
    cache_config = CacheConfig(block_size=16, num_device_blocks_override=64,
                               swap_space_gib=0.01)
    scheduler_config = SchedulerConfig(max_num_batched_tokens=2048,
                                       max_num_seqs=8, max_model_len=128,
                                       max_paddings=512)
    return LLMEngine(model_config, cache_config,
                     ParallelConfig(tensor_parallel_size=tp),
                     scheduler_config, log_stats=False,
                     skip_tokenizer_init=True)


@requires_8_devices
def test_vocab_padding_shards_odd_vocab(example_prompts):
    """A vocab of 121 does not divide tp=4: embeddings and lm_head must be
    PADDED to 64*tp and sharded (reference pads the same way,
    `vocab_parallel_embedding.py:39`), not silently replicated — and
    greedy outputs must still match tp=1 exactly."""
    from intellillm_tpu.sampling_params import SamplingParams

    vocab = 121
    prompts = [[5, 9, 2, 7], [101, 3, 18], [120, 120, 1, 4, 6]]

    def run(tp):
        engine = _dummy_llama_engine(vocab, tp)
        params = SamplingParams(temperature=0.0, max_tokens=8,
                                ignore_eos=True)
        for i, ids in enumerate(prompts):
            engine.add_request(str(i), None, params,
                               prompt_token_ids=list(ids))
        results = {}
        while engine.has_unfinished_requests():
            for out in engine.step():
                if out.finished:
                    results[out.request_id] = out.outputs[0].token_ids
        return [results[str(i)] for i in range(len(prompts))], engine

    ref, _ = run(1)
    got, engine = run(4)
    assert got == ref
    assert all(all(t < vocab for t in ids) for ids in got)

    params = engine.worker.params
    embed = params["embed_tokens"]
    assert embed.shape[0] == 256                  # 121 → 64*tp multiple
    # Actually sharded over "model": each shard holds 1/4 of the rows.
    assert embed.sharding.shard_shape(embed.shape)[0] == 64
    head = params["lm_head"]
    assert head.shape[1] == 256
    assert head.sharding.shard_shape(head.shape)[1] == 64


@requires_8_devices
def test_lora_tp2_matches_merged_golden(tiny_llama_dir, example_prompts,
                                        tmp_path_factory):
    """LoRA x TP (VERDICT r3 item 9): an adapter served over a tp=2 mesh
    must emit the same greedy tokens as the single-device merged-weights
    golden (reference tests/lora run adapters under real TP workers)."""
    from intellillm_tpu.lora.request import LoRARequest
    from tests.lora.test_lora import make_adapter, make_merged_checkpoint

    root = tmp_path_factory.mktemp("lora-tp")
    ad = make_adapter(str(root / "ad"), seed=11, rank=8, alpha=16.0)
    merged = make_merged_checkpoint(tiny_llama_dir, ad, str(root / "m"))

    prompts = example_prompts[:3]
    golden, _ = _generate_greedy(merged, prompts, 8)

    llm = LLM(model=tiny_llama_dir, dtype="float32",
              tensor_parallel_size=2, num_device_blocks_override=128,
              max_model_len=128, max_num_seqs=8, max_paddings=512,
              swap_space=0.01, enable_lora=True, max_loras=2,
              max_lora_rank=8)
    outs = llm.generate(prompts,
                        SamplingParams(temperature=0.0, max_tokens=8),
                        lora_request=LoRARequest("ad", 1, ad))
    got = [o.outputs[0].token_ids for o in outs]
    assert got == golden
