"""Sequence-parallel prefill through the SERVING engine (VERDICT r3
item 7: SP must be an engine capability, not just a library).

A prompt past --sp-prefill-threshold prefills with its sequence dim
sharded over the mesh "data" axis via ring attention
(ops/ring_attention.py), then decodes normally from the paged KV pool.
Greedy tokens must match a single-device run exactly.
"""
import jax
import pytest

from intellillm_tpu import LLM, SamplingParams

requires_8_devices = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices")


def _llm(model_dir, **kw):
    kw.setdefault("max_paddings", 512)
    return LLM(model=model_dir, dtype="float32",
               num_device_blocks_override=128, max_model_len=128,
               max_num_seqs=8, swap_space=0.01, **kw)


@requires_8_devices
def test_sp_prefill_matches_single_device(tiny_llama_dir):
    # A long prompt (>= threshold) plus short ones in the same workload:
    # the long one must route through ring attention, the short ones
    # through the flash path, all matching the single-device run.
    # 96 tokens: over the SP threshold; the tight max_paddings budget
    # below keeps any sibling out of its prefill batch (rows == 1).
    long_prompt = " ".join(["the cat runs fast and the dog is slow"] * 12)
    prompts = [long_prompt, "hello my name is",
               "the capital of france is"]
    params = SamplingParams(temperature=0.0, max_tokens=12)

    ref = [o.outputs[0].token_ids
           for o in _llm(tiny_llama_dir).generate(prompts, params)]

    import intellillm_tpu.ops.ring_attention as ring_mod
    calls = {"n": 0}
    orig = ring_mod.ring_attention

    def counting(*a, **kw):
        calls["n"] += 1
        return orig(*a, **kw)

    ring_mod.ring_attention = counting
    try:
        llm = _llm(tiny_llama_dir, tensor_parallel_size=2,
                   data_parallel_size=4, sp_prefill_threshold=48,
                   max_paddings=40)
        got = [o.outputs[0].token_ids for o in llm.generate(prompts,
                                                            params)]
    finally:
        ring_mod.ring_attention = orig

    assert calls["n"] > 0, "long prompt never routed through ring attention"
    assert got == ref


@requires_8_devices
def test_sp_threshold_not_triggered_for_short_prompts(tiny_llama_dir):
    """Short prompts under the threshold must keep the flash path."""
    import intellillm_tpu.ops.ring_attention as ring_mod
    calls = {"n": 0}
    orig = ring_mod.ring_attention

    def counting(*a, **kw):
        calls["n"] += 1
        return orig(*a, **kw)

    ring_mod.ring_attention = counting
    try:
        llm = _llm(tiny_llama_dir, data_parallel_size=4,
                   sp_prefill_threshold=64)
        llm.generate(["hello my name is"],
                     SamplingParams(temperature=0.0, max_tokens=4))
    finally:
        ring_mod.ring_attention = orig
    assert calls["n"] == 0

@requires_8_devices
def test_sp_prefill_ulysses_mode_matches_single_device(tiny_llama_dir,
                                                       monkeypatch):
    """INTELLILLM_SP_MODE=ulysses routes the SP prefill through the
    all-to-all path; tokens must still match the single-device run.
    (tiny-llama has 2 kv heads — use dp=2 so heads divide the axis.)"""
    monkeypatch.setenv("INTELLILLM_SP_MODE", "ulysses")
    long_prompt = " ".join(["the cat runs fast and the dog is slow"] * 12)
    params = SamplingParams(temperature=0.0, max_tokens=12)

    ref = [o.outputs[0].token_ids
           for o in _llm(tiny_llama_dir).generate([long_prompt], params)]

    import intellillm_tpu.ops.ulysses_attention as ul_mod
    calls = {"n": 0}
    orig = ul_mod.ulysses_attention

    def counting(*a, **kw):
        calls["n"] += 1
        return orig(*a, **kw)

    ul_mod.ulysses_attention = counting
    try:
        llm = _llm(tiny_llama_dir, data_parallel_size=2,
                   sp_prefill_threshold=48, max_paddings=40)
        got = [o.outputs[0].token_ids
               for o in llm.generate([long_prompt], params)]
    finally:
        ul_mod.ulysses_attention = orig

    assert calls["n"] > 0, "ulysses path never engaged"
    assert got == ref
