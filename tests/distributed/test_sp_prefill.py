"""Multi-device prefill correctness through the SERVING engine.

Sequence-parallel (ring / Ulysses) prefill was tied to the removed
whole-prompt homogeneous prefill program; prompts now prefill as
chunked mixed-dispatch rows on every topology, and
`--sp-prefill-threshold` is accepted but inert (config.py logs the
warning). What must still hold — and what these tests pin — is output
equality: long prompts prefilled under tensor/data-parallel meshes must
produce greedy tokens identical to a single-device run, and the SP ops
must never be silently routed to (they would desync the paged KV pool).
"""
import jax
import pytest

from intellillm_tpu import LLM, SamplingParams

requires_8_devices = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices")


def _llm(model_dir, **kw):
    kw.setdefault("max_paddings", 512)
    return LLM(model=model_dir, dtype="float32",
               num_device_blocks_override=128, max_model_len=128,
               max_num_seqs=8, swap_space=0.01, **kw)


@requires_8_devices
def test_multi_device_prefill_matches_single_device(tiny_llama_dir):
    # A long prompt plus short ones in the same workload: all prefill as
    # mixed-dispatch chunks over the tp=2 x dp=4 mesh and must match the
    # single-device run token for token.
    long_prompt = " ".join(["the cat runs fast and the dog is slow"] * 12)
    prompts = [long_prompt, "hello my name is",
               "the capital of france is"]
    params = SamplingParams(temperature=0.0, max_tokens=12)

    ref = [o.outputs[0].token_ids
           for o in _llm(tiny_llama_dir).generate(prompts, params)]

    llm = _llm(tiny_llama_dir, tensor_parallel_size=2,
               data_parallel_size=4, sp_prefill_threshold=48,
               max_paddings=40)
    got = [o.outputs[0].token_ids for o in llm.generate(prompts, params)]

    assert got == ref


@requires_8_devices
def test_sp_threshold_is_inert_and_ring_never_engaged(tiny_llama_dir):
    """--sp-prefill-threshold must not route ANY prompt through the ring
    path (it would bypass the paged mixed dispatch): the op stays
    uncalled even for prompts past the threshold."""
    import intellillm_tpu.ops.ring_attention as ring_mod
    calls = {"n": 0}
    orig = ring_mod.ring_attention

    def counting(*a, **kw):
        calls["n"] += 1
        return orig(*a, **kw)

    ring_mod.ring_attention = counting
    try:
        llm = _llm(tiny_llama_dir, data_parallel_size=4,
                   sp_prefill_threshold=64)
        long_prompt = " ".join(["the cat runs fast and the dog"] * 12)
        llm.generate(["hello my name is", long_prompt],
                     SamplingParams(temperature=0.0, max_tokens=4))
    finally:
        ring_mod.ring_attention = orig
    assert calls["n"] == 0


@requires_8_devices
def test_multi_device_prefill_ulysses_env_matches_single_device(
        tiny_llama_dir, monkeypatch):
    """INTELLILLM_SP_MODE=ulysses (now a no-op for serving) must not
    change outputs: the dp=2 run still matches single-device exactly.
    (tiny-llama has 2 kv heads — dp=2 keeps heads dividing the axis.)"""
    monkeypatch.setenv("INTELLILLM_SP_MODE", "ulysses")
    long_prompt = " ".join(["the cat runs fast and the dog is slow"] * 12)
    params = SamplingParams(temperature=0.0, max_tokens=12)

    ref = [o.outputs[0].token_ids
           for o in _llm(tiny_llama_dir).generate([long_prompt], params)]

    llm = _llm(tiny_llama_dir, data_parallel_size=2,
               sp_prefill_threshold=48, max_paddings=40)
    got = [o.outputs[0].token_ids
           for o in llm.generate([long_prompt], params)]

    assert got == ref
