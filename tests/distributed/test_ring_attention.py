"""Ring attention (sequence parallelism) vs full attention on the
8-virtual-device mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

requires_8_devices = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices")

# Version gate: ring/ulysses attention are built on the top-level
# jax.shard_map API; on older jax the whole module is untestable.
pytestmark = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="jax.shard_map is absent on this jax version")


def _full_attention(q, k, v, scale, causal):
    b, l, h, d = q.shape
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32) * scale,
                   k.astype(jnp.float32))
    if causal:
        mask = jnp.arange(l)[:, None] >= jnp.arange(l)[None, :]
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bhqd", p, v.astype(jnp.float32))
    return out.swapaxes(1, 2)


@requires_8_devices
@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("n_shards", [4, 8])
def test_ring_attention_matches_full(causal, n_shards):
    from intellillm_tpu.ops.ring_attention import ring_attention

    rng = np.random.default_rng(0)
    b, l, h, d = 2, 16 * n_shards, 4, 32
    q = jnp.asarray(rng.standard_normal((b, l, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, l, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, l, h, d)), jnp.float32)

    mesh = Mesh(np.asarray(jax.devices()[:n_shards]), axis_names=("seq", ))
    out = ring_attention(q, k, v, mesh, "seq", causal=causal)
    ref = _full_attention(q, k, v, d**-0.5, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


@requires_8_devices
def test_ring_attention_gqa():
    from intellillm_tpu.ops.ring_attention import ring_attention

    rng = np.random.default_rng(1)
    b, l, hq, hkv, d = 1, 64, 8, 2, 32
    q = jnp.asarray(rng.standard_normal((b, l, hq, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, l, hkv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, l, hkv, d)), jnp.float32)

    mesh = Mesh(np.asarray(jax.devices()[:4]), axis_names=("seq", ))
    out = ring_attention(q, k, v, mesh, "seq", causal=True)
    kx = jnp.repeat(k, hq // hkv, axis=2)
    vx = jnp.repeat(v, hq // hkv, axis=2)
    ref = _full_attention(q, kx, vx, d**-0.5, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


@requires_8_devices
def test_ring_attention_output_stays_sharded():
    """The output keeps the sequence sharding — no gather to one device."""
    from intellillm_tpu.ops.ring_attention import ring_attention

    rng = np.random.default_rng(2)
    b, l, h, d = 1, 128, 2, 32
    q = jnp.asarray(rng.standard_normal((b, l, h, d)), jnp.float32)
    mesh = Mesh(np.asarray(jax.devices()[:8]), axis_names=("seq", ))
    out = ring_attention(q, q, q, mesh, "seq")
    assert out.sharding.shard_shape(out.shape)[1] == l // 8


@requires_8_devices
@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_full(causal):
    from intellillm_tpu.ops.ulysses_attention import ulysses_attention

    rng = np.random.default_rng(3)
    b, l, h, d, n = 2, 64, 8, 32, 4
    q = jnp.asarray(rng.standard_normal((b, l, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, l, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, l, h, d)), jnp.float32)

    mesh = Mesh(np.asarray(jax.devices()[:n]), axis_names=("seq", ))
    out = ulysses_attention(q, k, v, mesh, "seq", causal=causal)
    ref = _full_attention(q, k, v, d**-0.5, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


@requires_8_devices
def test_ulysses_gqa_and_ring_agree():
    from intellillm_tpu.ops.ring_attention import ring_attention
    from intellillm_tpu.ops.ulysses_attention import ulysses_attention

    rng = np.random.default_rng(4)
    b, l, hq, hkv, d, n = 1, 64, 8, 4, 32, 4
    q = jnp.asarray(rng.standard_normal((b, l, hq, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, l, hkv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, l, hkv, d)), jnp.float32)

    mesh = Mesh(np.asarray(jax.devices()[:n]), axis_names=("seq", ))
    out_u = ulysses_attention(q, k, v, mesh, "seq")
    out_r = ring_attention(q, k, v, mesh, "seq")
    np.testing.assert_allclose(np.asarray(out_u), np.asarray(out_r),
                               rtol=2e-4, atol=2e-4)


@requires_8_devices
def test_ulysses_rejects_indivisible_heads():
    from intellillm_tpu.ops.ulysses_attention import ulysses_attention

    mesh = Mesh(np.asarray(jax.devices()[:8]), axis_names=("seq", ))
    q = jnp.zeros((1, 64, 4, 32), jnp.float32)
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(q, q, q, mesh, "seq")
