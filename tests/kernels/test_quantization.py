"""int8 weight-only quantization numerics."""
import jax.numpy as jnp
import numpy as np

from intellillm_tpu.layers.quantization import (qmatmul, quantize_int8,
                                                quantize_int8_jax)


def test_quantize_roundtrip_error_small():
    rng = np.random.default_rng(0)
    w = rng.normal(size=(64, 32)).astype(np.float32) * 0.1
    qw = quantize_int8(w)
    deq = qw["q"].astype(np.float32) * qw["s"][None, :]
    rel = np.abs(deq - w).max() / np.abs(w).max()
    assert rel < 0.01  # < 1% of max magnitude per int8 per-channel


def test_qmatmul_matches_dequant_matmul():
    rng = np.random.default_rng(1)
    x = rng.normal(size=(4, 64)).astype(np.float32)
    w = rng.normal(size=(64, 32)).astype(np.float32) * 0.05
    qw = quantize_int8(w)
    out_q = np.asarray(qmatmul(jnp.asarray(x),
                               {"q": jnp.asarray(qw["q"]),
                                "s": jnp.asarray(qw["s"])}))
    deq = qw["q"].astype(np.float32) * qw["s"][None, :]
    out_ref = x @ deq
    np.testing.assert_allclose(out_q, out_ref, rtol=1e-3, atol=1e-3)


def test_qmatmul_passthrough_plain_weights():
    x = jnp.ones((2, 4))
    w = jnp.ones((4, 3))
    np.testing.assert_allclose(np.asarray(qmatmul(x, w)),
                               np.full((2, 3), 4.0))


def test_jax_variant_matches_numpy():
    rng = np.random.default_rng(2)
    w = rng.normal(size=(32, 16)).astype(np.float32)
    q_np = quantize_int8(w)
    q_jx = quantize_int8_jax(jnp.asarray(w))
    np.testing.assert_array_equal(q_np["q"], np.asarray(q_jx["q"]))
    np.testing.assert_allclose(q_np["s"], np.asarray(q_jx["s"]), rtol=1e-6)
