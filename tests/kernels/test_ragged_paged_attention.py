"""Ragged fused cache-write + attend kernel vs the incumbent composition
(reshape_and_cache then decode_attention_reference) — the golden oracle
the mixed path is pinned against. On TPU the Mosaic kernel compiles
natively; on CPU it runs under Pallas TPU interpret mode
(tests/kernels/conftest.py)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from intellillm_tpu.ops.ragged_attention import (
    ragged_fused_attention_reference)

requires_tpu = pytest.mark.kernel


def _mixed_batch(rng, *, hq, hkv, d, nb=64, bs=16, w=8,
                 ctx_lens=(1, 17, 63, 30, 31, 32, 0), dtype=np.float32):
    """A mixed batch: decode rows plus a chunk run (three consecutive
    rows of ONE sequence at positions 29/30/31 — rows 3..5 share a block
    table, each must see its predecessors' just-written K/V) plus a pad
    row (ctx 0, slot -1)."""
    b = len(ctx_lens)
    k_cache = jnp.asarray(rng.normal(size=(nb, hkv, bs, d)).astype(dtype))
    v_cache = jnp.asarray(rng.normal(size=(nb, hkv, bs, d)).astype(dtype))
    q = jnp.asarray(rng.normal(size=(b, 1, hq, d)).astype(np.float32))
    k_new = jnp.asarray(rng.normal(size=(b, hkv, d)).astype(np.float32))
    v_new = jnp.asarray(rng.normal(size=(b, hkv, d)).astype(np.float32))

    tables = rng.permutation(nb)[:b * w].reshape(b, w).astype(np.int32)
    # Rows 3..5 are the chunk run: one sequence, one table.
    tables[4] = tables[3]
    tables[5] = tables[3]
    slots = []
    for i, c in enumerate(ctx_lens):
        if c == 0:
            slots.append(-1)
            continue
        blk = int(tables[i, (c - 1) // bs])
        slots.append(blk * bs + (c - 1) % bs)
    return (q, k_new, v_new, k_cache, v_cache,
            jnp.asarray(np.asarray(slots, np.int32)), jnp.asarray(tables),
            jnp.asarray(np.asarray(ctx_lens, np.int32)))


def _run_both(args, scale, alibi_slopes=None, cache_cast=None):
    from intellillm_tpu.ops.pallas.ragged_paged_attention import (
        ragged_paged_attention)
    q, k_new, v_new, k_cache, v_cache, slots, tables, ctx = args
    if cache_cast is not None:
        k_cache = k_cache.astype(cache_cast)
        v_cache = v_cache.astype(cache_cast)
    out_k, kc_k, vc_k = ragged_paged_attention(
        q, k_new.astype(k_cache.dtype), v_new.astype(v_cache.dtype),
        k_cache, v_cache, slots, tables, ctx, scale, alibi_slopes)
    out_r, kc_r, vc_r = ragged_fused_attention_reference(
        q, k_new, v_new, k_cache, v_cache, slots, tables, ctx, scale,
        alibi_slopes)
    return (out_k, kc_k, vc_k), (out_r, kc_r, vc_r)


@requires_tpu
@pytest.mark.parametrize("hq,hkv", [(8, 8), (8, 2), (4, 1)])
def test_ragged_matches_incumbent_composition(hq, hkv):
    rng = np.random.default_rng(0)
    d = 128
    args = _mixed_batch(rng, hq=hq, hkv=hkv, d=d)
    (out_k, kc_k, vc_k), (out_r, kc_r, vc_r) = _run_both(args, d**-0.5)
    tol = 5e-3 if jax.default_backend() == "tpu" else 2e-3
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_r),
                               rtol=tol, atol=tol)
    # The in-grid write must leave the pool byte-identical to the
    # separate scatter pass (same dtype, no arithmetic on the way in).
    np.testing.assert_array_equal(np.asarray(kc_k), np.asarray(kc_r))
    np.testing.assert_array_equal(np.asarray(vc_k), np.asarray(vc_r))


@requires_tpu
def test_ragged_chunk_rows_see_in_flight_writes():
    """The chunk-run rows (3..5) attend to positions written by the rows
    just before them in the SAME kernel launch — the write-before-read
    ordering the sequential grid guarantees. A kernel that read stale
    pages for its predecessor's token would diverge from the oracle
    exactly on rows 4 and 5."""
    rng = np.random.default_rng(2)
    d, hq, hkv = 128, 4, 2
    args = _mixed_batch(rng, hq=hq, hkv=hkv, d=d)
    (out_k, _, _), (out_r, _, _) = _run_both(args, d**-0.5)
    tol = 5e-3 if jax.default_backend() == "tpu" else 2e-3
    np.testing.assert_allclose(np.asarray(out_k)[4:6],
                               np.asarray(out_r)[4:6],
                               rtol=tol, atol=tol)


@requires_tpu
def test_ragged_alibi_matches_incumbent():
    from intellillm_tpu.layers.alibi import get_alibi_slopes
    rng = np.random.default_rng(3)
    d, hq, hkv = 128, 8, 2
    slopes = jnp.asarray(get_alibi_slopes(hq), jnp.float32)
    args = _mixed_batch(rng, hq=hq, hkv=hkv, d=d)
    (out_k, _, _), (out_r, _, _) = _run_both(args, d**-0.5,
                                             alibi_slopes=slopes)
    tol = 2e-2 if jax.default_backend() == "tpu" else 2e-3
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_r),
                               rtol=tol, atol=tol)


@requires_tpu
def test_ragged_bf16_cache_self_token_uses_cast_values():
    """With a bf16 pool the self-token must contribute its POST-cast
    value (the reference reads the cache after the write); a kernel that
    attended over the f32 pre-cast k_new/v_new would drift on exactly
    the ctx=1 row, where the self token is the whole softmax."""
    rng = np.random.default_rng(4)
    d, hq, hkv = 128, 4, 2
    args = _mixed_batch(rng, hq=hq, hkv=hkv, d=d,
                        ctx_lens=(1, 1, 5, 40, 1, 2, 0))
    (out_k, kc_k, vc_k), (out_r, kc_r, vc_r) = _run_both(
        args, d**-0.5, cache_cast=jnp.bfloat16)
    np.testing.assert_allclose(np.asarray(out_k, np.float32),
                               np.asarray(out_r, np.float32),
                               rtol=2e-2, atol=2e-2)
    np.testing.assert_array_equal(
        np.asarray(kc_k.astype(jnp.float32)),
        np.asarray(kc_r.astype(jnp.float32)))
    np.testing.assert_array_equal(
        np.asarray(vc_k.astype(jnp.float32)),
        np.asarray(vc_r.astype(jnp.float32)))


@requires_tpu
def test_ragged_rejects_uncast_kv():
    from intellillm_tpu.ops.pallas.ragged_paged_attention import (
        ragged_paged_attention)
    rng = np.random.default_rng(5)
    d, hq, hkv = 128, 4, 2
    q, k_new, v_new, k_cache, v_cache, slots, tables, ctx = _mixed_batch(
        rng, hq=hq, hkv=hkv, d=d)
    with pytest.raises(ValueError, match="pre-cast"):
        ragged_paged_attention(q, k_new, v_new,
                               k_cache.astype(jnp.bfloat16),
                               v_cache.astype(jnp.bfloat16), slots,
                               tables, ctx, d**-0.5)
