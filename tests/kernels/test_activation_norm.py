"""Activation and normalization layers vs torch reference formulas.

Role parity: reference `tests/kernels/test_activation.py` (SiluAndMul,
NewGELU, FastGELU vs torch) and `tests/kernels/test_layernorm.py`
(RMSNorm with/without residual vs a float32 reference).
"""
import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn.functional as F

from intellillm_tpu.layers.activation import (gelu_fast, gelu_new,
                                              get_act_fn, silu_and_mul)
from intellillm_tpu.layers.normalization import (fused_add_rms_norm,
                                                 layer_norm, rms_norm)


@pytest.mark.parametrize("shape", [(7, 128), (2, 5, 64)])
def test_silu_and_mul_matches_torch(shape):
    rng = np.random.default_rng(0)
    x = rng.standard_normal(shape[:-1] + (2 * shape[-1], )
                            ).astype(np.float32)
    t = torch.from_numpy(x)
    ref = (F.silu(t[..., :shape[-1]]) * t[..., shape[-1]:]).numpy()
    got = np.asarray(silu_and_mul(jnp.asarray(x)))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_gelu_new_matches_hf():
    from transformers.activations import NewGELUActivation
    rng = np.random.default_rng(1)
    x = rng.standard_normal((11, 96)).astype(np.float32)
    ref = NewGELUActivation()(torch.from_numpy(x)).numpy()
    got = np.asarray(gelu_new(jnp.asarray(x)))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_gelu_fast_matches_hf():
    from transformers.activations import FastGELUActivation
    rng = np.random.default_rng(2)
    x = rng.standard_normal((11, 96)).astype(np.float32)
    ref = FastGELUActivation()(torch.from_numpy(x)).numpy()
    got = np.asarray(gelu_fast(jnp.asarray(x)))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_get_act_fn_known_and_unknown():
    assert get_act_fn("gelu_new") is gelu_new
    with pytest.raises((KeyError, ValueError)):
        get_act_fn("definitely-not-an-activation")


@pytest.mark.parametrize("eps", [1e-6, 1e-5])
def test_rms_norm_matches_reference(eps):
    rng = np.random.default_rng(3)
    x = rng.standard_normal((5, 17, 64)).astype(np.float32)
    w = rng.standard_normal(64).astype(np.float32)
    var = (x.astype(np.float64) ** 2).mean(-1, keepdims=True)
    ref = (x / np.sqrt(var + eps) * w).astype(np.float32)
    got = np.asarray(rms_norm(jnp.asarray(x), jnp.asarray(w), eps))
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_fused_add_rms_norm_matches_unfused():
    rng = np.random.default_rng(4)
    x = rng.standard_normal((3, 9, 64)).astype(np.float32)
    res = rng.standard_normal((3, 9, 64)).astype(np.float32)
    w = rng.standard_normal(64).astype(np.float32)
    eps = 1e-6
    summed = x + res
    ref = np.asarray(rms_norm(jnp.asarray(summed), jnp.asarray(w), eps))
    got, new_res = fused_add_rms_norm(jnp.asarray(x), jnp.asarray(res),
                                      jnp.asarray(w), eps)
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(new_res), summed,
                               rtol=1e-6, atol=1e-6)


def test_layer_norm_matches_torch():
    rng = np.random.default_rng(5)
    x = rng.standard_normal((4, 8, 32)).astype(np.float32)
    w = rng.standard_normal(32).astype(np.float32)
    b = rng.standard_normal(32).astype(np.float32)
    ref = F.layer_norm(torch.from_numpy(x), (32, ),
                       torch.from_numpy(w), torch.from_numpy(b)).numpy()
    got = np.asarray(layer_norm(jnp.asarray(x), jnp.asarray(w),
                                jnp.asarray(b)))
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)
