"""context_attention_reference (prefix-cached prefill) vs full prefill.

The suffix tokens' outputs must match running the whole [prefix ++ suffix]
prompt through plain prefill attention — including sliding-window and
ALiBi variants (ADVICE r1: the window previously ignored the cached
prefix).
"""
import jax.numpy as jnp
import numpy as np
import pytest

from intellillm_tpu.layers.alibi import get_alibi_slopes
from intellillm_tpu.ops.attention import (context_attention_reference,
                                          prefill_attention_reference)
from intellillm_tpu.ops.kv_cache import reshape_and_cache


def _run_pair(hq, hkv, sliding_window=None, use_alibi=False, seed=0):
    rng = np.random.default_rng(seed)
    b, p, l, d, bs = 2, 8, 5, 16, 4
    total = p + l
    q = rng.normal(size=(b, total, hq, d)).astype(np.float32)
    k = rng.normal(size=(b, total, hkv, d)).astype(np.float32)
    v = rng.normal(size=(b, total, hkv, d)).astype(np.float32)
    scale = d**-0.5
    slopes = (jnp.asarray(get_alibi_slopes(hq), jnp.float32)
              if use_alibi else None)

    # Oracle: full prompt through plain prefill attention.
    full = prefill_attention_reference(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        jnp.full((b,), total, jnp.int32), scale, sliding_window, slopes)
    expect = np.asarray(full)[:, p:]

    # Prefix path: cache the first p tokens' KV in a block pool.
    nblocks_per_seq = p // bs
    nb = b * nblocks_per_seq + 1
    k_cache = jnp.zeros((nb, hkv, bs, d), jnp.float32)
    v_cache = jnp.zeros((nb, hkv, bs, d), jnp.float32)
    tables = np.zeros((b, nblocks_per_seq), np.int32)
    slot_rows = []
    for i in range(b):
        blocks = np.arange(nblocks_per_seq) + i * nblocks_per_seq + 1
        tables[i] = blocks
        slot_rows.append((blocks[:, None] * bs +
                          np.arange(bs)[None]).reshape(-1))
    slots = np.concatenate(slot_rows).astype(np.int32)
    k_pre = jnp.asarray(k[:, :p].reshape(b * p, hkv, d))
    v_pre = jnp.asarray(v[:, :p].reshape(b * p, hkv, d))
    k_cache, v_cache = reshape_and_cache(k_pre, v_pre, k_cache, v_cache,
                                         jnp.asarray(slots))

    out = context_attention_reference(
        jnp.asarray(q[:, p:]), jnp.asarray(k[:, p:]), jnp.asarray(v[:, p:]),
        k_cache, v_cache, jnp.asarray(tables),
        jnp.full((b,), p, jnp.int32), jnp.full((b,), l, jnp.int32),
        scale, slopes, sliding_window)
    np.testing.assert_allclose(np.asarray(out), expect, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("hq,hkv", [(4, 4), (4, 2)])
def test_context_attention_matches_full_prefill(hq, hkv):
    _run_pair(hq, hkv)


@pytest.mark.parametrize("window", [4, 7])
def test_context_attention_sliding_window(window):
    """Windowed prefix attention must match the windowed full-prompt path
    (previously the cached prefix ignored the window entirely)."""
    _run_pair(4, 2, sliding_window=window)


def test_context_attention_alibi():
    _run_pair(4, 4, use_alibi=True)
