"""Kernel-test fixtures.

On CPU the Pallas TPU kernels run under `force_tpu_interpret_mode`, so
the whole kernel grid is exercised (numerics, masking, block-table walk)
without TPU hardware; on a real TPU the same tests compile and run the
Mosaic kernels natively.
"""
import jax
import pytest
from jax.experimental.pallas import tpu as pltpu


@pytest.fixture(autouse=True)
def _tpu_interpret_on_cpu():
    if jax.default_backend() == "tpu":
        yield
    elif not hasattr(pltpu, "force_tpu_interpret_mode"):
        # Version gate: without interpret mode the Mosaic kernels cannot
        # run off-TPU at all — skip instead of erroring every kernel test
        # on jax versions that predate the API.
        pytest.skip("pallas force_tpu_interpret_mode is absent on this "
                    "jax version; kernel grids need TPU or interpret mode")
    else:
        with pltpu.force_tpu_interpret_mode():
            yield
