"""Pallas paged-attention kernel vs the jnp reference over a parameter
grid (reference pattern: `tests/kernels/test_attention.py` sweeps dtypes ×
head configs × block sizes against `ref_single_query_cached_kv_attention`).

On TPU the Mosaic kernel compiles natively; on CPU it runs under
Pallas TPU interpret mode (tests/kernels/conftest.py), so the grid is
exercised everywhere.

One kernel, one grid of tests: the old v3/v4 twin modules were
consolidated — the head-block-vectorized (v4) kernel is the only
implementation, so the former per-variant fixtures and the v3/v4
cross-consistency check are gone with the twin.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from intellillm_tpu.ops.attention import decode_attention_reference
from intellillm_tpu.ops.pallas.paged_attention import paged_attention

# On CPU the kernels run in TPU interpret mode (see conftest.py);
# the marker is kept as documentation of the native target.
requires_tpu = pytest.mark.kernel


def make_cache(rng, nb, hkv, bs, d, dtype):
    k = rng.normal(size=(nb, hkv, bs, d)).astype(dtype)
    v = rng.normal(size=(nb, hkv, bs, d)).astype(dtype)
    return jnp.asarray(k), jnp.asarray(v)


def _oracle_tol(use_alibi: bool) -> float:
    # Real-TPU ALiBi runs land up to ~9e-3 off the f32 jnp oracle (online
    # vs full softmax rounding under large negative biases). CPU interpret
    # mode keeps a tight bound so kernel-logic regressions fail loudly.
    if jax.default_backend() == "tpu":
        return 2e-2 if use_alibi else 5e-3
    return 2e-3


@requires_tpu
@pytest.mark.parametrize("hq,hkv", [(8, 8), (8, 2), (4, 1)])
@pytest.mark.parametrize("w", [8, 16])    # w=16 exercises ppg=16 groups
@pytest.mark.parametrize("use_alibi", [False, True])
def test_paged_attention_matches_reference(hq, hkv, w, use_alibi):
    """The consolidated kernel vs the jnp oracle over head configs ×
    table widths × ALiBi, including the logsumexp output."""
    rng = np.random.default_rng(0)
    b, d, bs = 4, 128, 16
    nb = b * w + 8
    k_cache, v_cache = make_cache(rng, nb, hkv, bs, d, np.float32)
    q = jnp.asarray(rng.normal(size=(b, 1, hq, d)).astype(np.float32))
    tables = jnp.asarray(
        rng.permutation(nb)[:b * w].reshape(b, w).astype(np.int32))
    ctx = jnp.asarray(np.asarray([1, 17, 63, w * bs], np.int32))
    slopes = (jnp.asarray(rng.random(hq).astype(np.float32))
              if use_alibi else None)

    out, lse = paged_attention(q, k_cache, v_cache, tables, ctx,
                               d**-0.5, slopes, return_lse=True)
    ref, ref_lse = decode_attention_reference(q, k_cache, v_cache, tables,
                                              ctx, d**-0.5, slopes,
                                              return_lse=True)
    tol = _oracle_tol(use_alibi)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=tol, atol=tol)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse),
                               rtol=tol, atol=tol)


@requires_tpu
def test_paged_attention_lse_matches_reference():
    rng = np.random.default_rng(1)
    b, hq, hkv, d, nb, bs, w = 2, 4, 2, 128, 32, 16, 4
    k_cache, v_cache = make_cache(rng, nb, hkv, bs, d, np.float32)
    q = jnp.asarray(rng.normal(size=(b, 1, hq, d)).astype(np.float32))
    block_tables = jnp.asarray(
        rng.permutation(nb)[:b * w].reshape(b, w).astype(np.int32))
    context_lens = jnp.asarray(np.asarray([5, 40], np.int32))
    scale = d**-0.5

    out_k, lse_k = paged_attention(q, k_cache, v_cache, block_tables,
                                   context_lens, scale, return_lse=True)
    out_r, lse_r = decode_attention_reference(q, k_cache, v_cache,
                                              block_tables, context_lens,
                                              scale, return_lse=True)
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_r),
                               rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(np.asarray(lse_k), np.asarray(lse_r),
                               rtol=2e-2, atol=2e-2)


@requires_tpu
@pytest.mark.parametrize("hq,hkv", [(8, 8), (8, 2)])
def test_paged_attention_alibi_matches_reference(hq, hkv):
    """ALiBi bias is computed natively inside the kernel; previously this
    configuration fell back to the jnp gather path."""
    from intellillm_tpu.layers.alibi import get_alibi_slopes

    rng = np.random.default_rng(3)
    b, d, nb, bs, w = 4, 128, 64, 16, 8
    k_cache, v_cache = make_cache(rng, nb, hkv, bs, d, np.float32)
    q = jnp.asarray(rng.normal(size=(b, 1, hq, d)).astype(np.float32))
    tables = rng.permutation(nb)[:b * w].reshape(b, w).astype(np.int32)
    context_lens = jnp.asarray(np.asarray([1, 17, 63, 128], np.int32))
    slopes = jnp.asarray(get_alibi_slopes(hq), jnp.float32)
    scale = d**-0.5

    out_k = paged_attention(q, k_cache, v_cache, jnp.asarray(tables),
                            context_lens, scale, alibi_slopes=slopes)
    out_r = decode_attention_reference(q, k_cache, v_cache,
                                       jnp.asarray(tables), context_lens,
                                       scale, alibi_slopes=slopes)
    tol = _oracle_tol(use_alibi=True)
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_r),
                               rtol=tol, atol=tol)


@requires_tpu
def test_paged_attention_bf16_cache_wide_table():
    """bf16 KV with a 32-wide block table (llama-7b decode shape at
    max_model_len=512): ppg hits its 16-page cap, giving the largest
    VMEM double-buffer the kernel ever allocates for 2-byte caches —
    validated on real v5e (the f32 grid above is 2x larger still)."""
    rng = np.random.default_rng(7)
    b, d, bs, hq, hkv, w = 4, 128, 16, 32, 32, 32
    nb = b * w + 8
    k_cache, v_cache = make_cache(rng, nb, hkv, bs, d, np.float32)
    k_cache = k_cache.astype(jnp.bfloat16)
    v_cache = v_cache.astype(jnp.bfloat16)
    q = jnp.asarray(rng.normal(size=(b, 1, hq, d)).astype(np.float32)
                    ).astype(jnp.bfloat16)
    tables = jnp.asarray(
        rng.permutation(nb)[:b * w].reshape(b, w).astype(np.int32))
    ctx = jnp.asarray(np.asarray([1, 100, 300, w * bs], np.int32))

    out = paged_attention(q, k_cache, v_cache, tables, ctx, d**-0.5)
    ref = decode_attention_reference(q, k_cache, v_cache, tables, ctx,
                                     d**-0.5)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=2e-2, atol=2e-2)


@requires_tpu
def test_paged_v4_flag_is_inert_and_warns():
    """INTELLILLM_PAGED_V4=0 used to select the deleted v3 twin; it must
    now warn (stale launch configs surface) and still run the kernel."""
    import os
    rng = np.random.default_rng(5)
    b, hq, hkv, d, nb, bs, w = 2, 4, 2, 128, 32, 16, 4
    k_cache, v_cache = make_cache(rng, nb, hkv, bs, d, np.float32)
    q = jnp.asarray(rng.normal(size=(b, 1, hq, d)).astype(np.float32))
    tables = jnp.asarray(
        rng.permutation(nb)[:b * w].reshape(b, w).astype(np.int32))
    ctx = jnp.asarray(np.asarray([5, 40], np.int32))

    env = dict(os.environ)
    try:
        os.environ["INTELLILLM_PAGED_V4"] = "0"
        with pytest.warns(UserWarning, match="consolidated"):
            out = paged_attention(q, k_cache, v_cache, tables, ctx,
                                  d**-0.5)
    finally:
        os.environ.clear()
        os.environ.update(env)
    ref = decode_attention_reference(q, k_cache, v_cache, tables, ctx,
                                     d**-0.5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=5e-3, atol=5e-3)
