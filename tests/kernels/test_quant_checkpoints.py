"""AWQ / GPTQ / SqueezeLLM checkpoint loading tests.

Reference roles: `tests/kernels/test_awq.py`-style dequant checks +
loading paths of `layers/quantization/{awq,gptq,squeezellm}.py`.

Golden strategy: pack synthetic int4 tensors into the exact on-disk
formats, then
- unpack/dequant must invert the packer bit-exactly;
- an engine serving the AWQ checkpoint must emit the same greedy tokens
  as an engine serving an fp checkpoint holding the dequantized weights
  (the int4 device path computes (q-z)*s in f32 — identical math);
- GPTQ/SqueezeLLM load to int8, so their golden twin is the dequantized
  fp checkpoint served with quantization="int8" (identical int8 repr).
"""
import json
import os

import jax.numpy as jnp
import numpy as np
import pytest
import torch

from intellillm_tpu.layers.quantization import (_AWQ_ORDER, awq_unpack,
                                                gptq_dequantize, pack_int4,
                                                qmatmul, quantize_int4,
                                                squeezellm_dequantize)

# --- test-side packers (replicating the public on-disk formats) ----------


def awq_pack_cols(m: np.ndarray) -> np.ndarray:
    """[R, C] nibbles → int32 [R, C/8] with AWQ nibble order."""
    r, c = m.shape
    out = np.zeros((r, c // 8), np.uint32)
    for i in range(8):
        out |= m[:, _AWQ_ORDER[i]::8].astype(np.uint32) << (4 * i)
    return out.view(np.int32)


def gptq_pack_rows(m: np.ndarray) -> np.ndarray:
    """[R, C] nibbles → int32 [R/8, C] sequential along rows."""
    r, c = m.shape
    out = np.zeros((r // 8, c), np.uint32)
    for i in range(8):
        out |= m[i::8, :].astype(np.uint32) << (4 * i)
    return out.view(np.int32)


def gptq_pack_cols(m: np.ndarray) -> np.ndarray:
    """[R, C] nibbles → int32 [R, C/8] sequential along cols."""
    r, c = m.shape
    out = np.zeros((r, c // 8), np.uint32)
    for i in range(8):
        out |= m[:, i::8].astype(np.uint32) << (4 * i)
    return out.view(np.int32)


def _rand_qzs(rng, in_, out, group):
    q = rng.integers(0, 16, size=(in_, out)).astype(np.uint8)
    z = rng.integers(0, 16, size=(in_ // group, out)).astype(np.uint8)
    s = (rng.random((in_ // group, out)).astype(np.float32) + 0.1)
    return q, z, s


# --- unit: converters -----------------------------------------------------


def test_awq_unpack_roundtrip():
    rng = np.random.default_rng(0)
    q, z, s = _rand_qzs(rng, 32, 16, 8)
    qw = awq_pack_cols(q)
    qz = awq_pack_cols(z)
    q2, z2, s2 = awq_unpack(qw, qz, s.astype(np.float16))
    np.testing.assert_array_equal(q2, q)
    np.testing.assert_array_equal(z2, z.astype(np.float32))
    np.testing.assert_allclose(s2, s.astype(np.float16), rtol=1e-3)


def _rand_gptq(rng, in_, out, group):
    """GPTQ zeros are 1..16 on disk-minus-one (dequant adds 1 back)."""
    q = rng.integers(0, 16, size=(in_, out)).astype(np.uint8)
    z = rng.integers(1, 17, size=(in_ // group, out)).astype(np.int32)
    s = (rng.random((in_ // group, out)).astype(np.float32) + 0.1)
    return q, z, s


def test_gptq_dequantize_matches_reference():
    rng = np.random.default_rng(1)
    in_, out, group = 32, 16, 8
    q, z, s = _rand_gptq(rng, in_, out, group)
    qweight = gptq_pack_rows(q)
    qzeros = gptq_pack_cols((z - 1).astype(np.uint8))  # stored z-1
    g_idx = np.arange(in_) // group
    w = gptq_dequantize(qweight, qzeros, s, g_idx)
    ref = (q.astype(np.float32) - z[g_idx]) * s[g_idx]
    np.testing.assert_allclose(w, ref, rtol=1e-6)


def test_gptq_act_order():
    rng = np.random.default_rng(2)
    in_, out, group = 32, 16, 8
    q, z, s = _rand_gptq(rng, in_, out, group)
    g_idx = rng.integers(0, in_ // group, size=in_)   # scrambled act-order
    qweight = gptq_pack_rows(q)
    qzeros = gptq_pack_cols((z - 1).astype(np.uint8))
    w = gptq_dequantize(qweight, qzeros, s, g_idx)
    ref = (q.astype(np.float32) - z[g_idx]) * s[g_idx]
    np.testing.assert_allclose(w, ref, rtol=1e-6)


@pytest.mark.parametrize("scramble", [False, True])
def test_gptq_to_int4_lossless(scramble):
    """gptq_to_int4 + qmatmul must reproduce the exact dequant math,
    including act-order checkpoints (activation permutation)."""
    from intellillm_tpu.layers.quantization import gptq_to_int4

    rng = np.random.default_rng(6)
    in_, out, group = 32, 16, 8
    q, z, s = _rand_gptq(rng, in_, out, group)
    g_idx = np.arange(in_) // group
    if scramble:
        g_idx = g_idx[rng.permutation(in_)]
    qweight = gptq_pack_rows(q)
    qzeros = gptq_pack_cols((z - 1).astype(np.uint8))
    packed = gptq_to_int4(qweight, qzeros, s, g_idx)
    assert packed is not None
    assert ("perm" in packed) == scramble
    packed = {k: jnp.asarray(v) for k, v in packed.items()}
    wf = (q.astype(np.float32) - z[g_idx]) * s[g_idx]     # exact dequant
    x = rng.standard_normal((3, in_)).astype(np.float32)
    ref = x @ wf
    got = np.asarray(qmatmul(jnp.asarray(x), packed))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_gptq_to_int4_irregular_groups_rejected():
    """Unbalanced g_idx (a group with the wrong row count) must return
    None so the loader falls back to int8 requantization."""
    from intellillm_tpu.layers.quantization import gptq_to_int4

    rng = np.random.default_rng(7)
    in_, out, group = 32, 16, 8
    q, z, s = _rand_gptq(rng, in_, out, group)
    g_idx = np.zeros(in_, np.int32)        # everything in group 0
    assert gptq_to_int4(gptq_pack_rows(q),
                        gptq_pack_cols((z - 1).astype(np.uint8)),
                        s, g_idx) is None


def test_squeezellm_dequantize():
    rng = np.random.default_rng(3)
    in_, out = 16, 8
    q = rng.integers(0, 16, size=(in_, out)).astype(np.uint8)
    lut = rng.random((out, 16)).astype(np.float32)
    w = squeezellm_dequantize(gptq_pack_rows(q), lut)
    ref = np.stack([lut[o, q[:, o]] for o in range(out)], axis=1)
    np.testing.assert_allclose(w, ref)


def test_int4_qmatmul_matches_dequant():
    rng = np.random.default_rng(4)
    q, z, s = _rand_qzs(rng, 32, 16, 8)
    packed = pack_int4(q, z, s)
    packed = {k: jnp.asarray(v) for k, v in packed.items()}
    x = rng.standard_normal((3, 32)).astype(np.float32)
    wf = (q.astype(np.float32).reshape(4, 8, 16) - z[:, None]) * s[:, None]
    ref = x @ wf.reshape(32, 16)
    out = np.asarray(qmatmul(jnp.asarray(x), packed))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_quantize_int4_error_bound():
    rng = np.random.default_rng(5)
    w = rng.standard_normal((64, 24)).astype(np.float32)
    packed = quantize_int4(w, group_size=16)
    packed = {k: jnp.asarray(v) for k, v in packed.items()}
    x = jnp.eye(64, dtype=jnp.float32)
    wd = np.asarray(qmatmul(x, packed))
    # Max error <= scale/2 per group.
    g = w.reshape(4, 16, 24)
    max_scale = (g.max(1) - g.min(1)).max() / 15.0
    assert np.abs(wd - w).max() <= max_scale / 2 + 1e-6


# --- e2e: engine on quantized checkpoints --------------------------------


def _awqify_checkpoint(base_dir, out_dir, group=16):
    """Convert a tiny fp llama checkpoint into (awq_dir, fp_twin_dir)."""
    import safetensors.numpy
    from transformers import AutoModelForCausalLM, AutoTokenizer

    model = AutoModelForCausalLM.from_pretrained(base_dir,
                                                 torch_dtype=torch.float32)
    sd = {k: v.numpy() for k, v in model.state_dict().items()}
    targets = [k for k in sd
               if k.endswith("_proj.weight") and "layers" in k]
    tensors = {}
    twin_sd = dict(sd)
    for name in sd:
        if name not in targets:
            tensors[name] = sd[name]
    for name in targets:
        wt = sd[name]                        # [out, in] torch layout
        w = wt.T.astype(np.float32)          # [in, out]
        in_, out = w.shape
        g = in_ // group
        wg = w.reshape(g, group, out)
        wmin, wmax = wg.min(1), wg.max(1)
        s = np.maximum((wmax - wmin) / 15.0, 1e-8).astype(np.float32)
        z = np.round(-wmin / s).clip(0, 15).astype(np.uint8)
        q = np.clip(np.round(wg / s[:, None] + z[:, None]), 0,
                    15).astype(np.uint8).reshape(in_, out)
        deq = ((q.astype(np.float32).reshape(g, group, out) -
                z[:, None]) * s[:, None]).reshape(in_, out)
        prefix = name[:-len(".weight")]
        tensors[prefix + ".qweight"] = awq_pack_cols(q)
        tensors[prefix + ".qzeros"] = awq_pack_cols(z)
        tensors[prefix + ".scales"] = s
        twin_sd[name] = deq.T.astype(np.float32)

    os.makedirs(out_dir + "-awq", exist_ok=True)
    safetensors.numpy.save_file(
        {k: np.ascontiguousarray(v) for k, v in tensors.items()},
        os.path.join(out_dir + "-awq", "model.safetensors"))
    with open(os.path.join(base_dir, "config.json")) as f:
        cfg = json.load(f)
    cfg["quantization_config"] = {"quant_method": "awq", "bits": 4,
                                  "group_size": group, "zero_point": True,
                                  "version": "gemm"}
    with open(os.path.join(out_dir + "-awq", "config.json"), "w") as f:
        json.dump(cfg, f)
    AutoTokenizer.from_pretrained(base_dir).save_pretrained(out_dir + "-awq")

    model.load_state_dict({k: torch.from_numpy(np.ascontiguousarray(v))
                           for k, v in twin_sd.items()})
    model.save_pretrained(out_dir + "-twin", safe_serialization=True)
    AutoTokenizer.from_pretrained(base_dir).save_pretrained(
        out_dir + "-twin")
    return out_dir + "-awq", out_dir + "-twin"


def _greedy(model_dir, prompts, **kw):
    from intellillm_tpu import LLM, SamplingParams
    llm = LLM(model=model_dir, dtype="float32",
              num_device_blocks_override=128, max_model_len=64,
              max_num_seqs=8, swap_space=0.01, **kw)
    outs = llm.generate(prompts, SamplingParams(temperature=0.0,
                                                max_tokens=8))
    return [o.outputs[0].token_ids for o in outs]


def test_awq_checkpoint_matches_dequant_twin(tiny_llama_dir, tmp_path,
                                             example_prompts):
    """Loaded AWQ params must dequantize BIT-EXACTLY to the fp twin's
    weights across the whole tree, and first greedy tokens must agree.

    (Full token-sequence equality is NOT asserted: the dequant-operand
    matmul and the plain-parameter matmul accumulate fp32 in different
    orders under XLA fusion, which flips greedy near-ties on tiny random
    models even though the weights are identical.)
    """
    import jax
    from intellillm_tpu.config import ModelConfig
    from intellillm_tpu.layers.quantization import _dequant_int4
    from intellillm_tpu.models.model_loader import get_model

    awq_dir, twin_dir = _awqify_checkpoint(tiny_llama_dir,
                                           str(tmp_path / "ck"))
    mc_awq = ModelConfig(model=awq_dir, dtype="float32")
    assert mc_awq.quantization == "awq"   # auto-detected from the config
    _, params_awq = get_model(mc_awq)
    _, params_twin = get_model(ModelConfig(model=twin_dir, dtype="float32"))

    def compare(a, t):
        if isinstance(a, dict) and "q4" in a:
            deq = np.asarray(_dequant_int4(
                {k: jnp.asarray(v) for k, v in a.items()}, jnp.float32))
            np.testing.assert_array_equal(deq, np.asarray(t))
        elif isinstance(a, dict):
            for k in a:
                compare(a[k], t[k])
        elif isinstance(a, list):
            for x, y in zip(a, t):
                compare(x, y)
        elif a is not None:
            np.testing.assert_array_equal(np.asarray(a), np.asarray(t))

    compare(params_awq, params_twin)

    golden = _greedy(twin_dir, example_prompts)
    ours = _greedy(awq_dir, example_prompts)
    for g, o in zip(golden, ours):
        assert g[0] == o[0]


def _gptqify_checkpoint(base_dir, tmp_path, group=16, act_order=False):
    """Convert a tiny fp llama checkpoint into (gptq_dir, fp_twin_dir);
    act_order scrambles each weight's g_idx (balanced groups)."""
    import safetensors.numpy
    from transformers import AutoModelForCausalLM, AutoTokenizer

    model = AutoModelForCausalLM.from_pretrained(base_dir,
                                                 torch_dtype=torch.float32)
    sd = {k: v.numpy() for k, v in model.state_dict().items()}
    targets = [k for k in sd
               if k.endswith("_proj.weight") and "layers" in k]
    tensors = {k: v for k, v in sd.items() if k not in targets}
    twin_sd = dict(sd)
    rng = np.random.default_rng(0)
    for name in targets:
        w = sd[name].T.astype(np.float32)
        in_, out = w.shape
        g = in_ // group
        g_idx = (np.arange(in_) // group).astype(np.int32)
        if act_order:
            # A row-permuted (still balanced) group assignment: what a
            # desc_act checkpoint looks like after GPTQ reorders columns
            # by activation magnitude.
            g_idx = g_idx[rng.permutation(in_)]
        wg = np.stack([w[g_idx == j] for j in range(g)])   # [g, group, out]
        wmin, wmax = wg.min(1), wg.max(1)
        s = np.maximum((wmax - wmin) / 15.0, 1e-8).astype(np.float32)
        z = np.round(-wmin / s).clip(1, 15).astype(np.uint8)  # z-1 >= 0
        q = np.zeros((in_, out), np.uint8)
        deq = np.zeros((in_, out), np.float32)
        for j in range(g):
            rows = np.flatnonzero(g_idx == j)
            qj = np.clip(np.round(w[rows] / s[j] + z[j]), 0,
                         15).astype(np.uint8)
            q[rows] = qj
            deq[rows] = (qj.astype(np.float32) - z[j]) * s[j]
        prefix = name[:-len(".weight")]
        tensors[prefix + ".qweight"] = gptq_pack_rows(q)
        tensors[prefix + ".qzeros"] = gptq_pack_cols(
            (z.astype(np.int32) - 1).astype(np.uint8))
        tensors[prefix + ".scales"] = s
        tensors[prefix + ".g_idx"] = g_idx
        twin_sd[name] = deq.T.astype(np.float32)

    gptq_dir = str(tmp_path / "gptq")
    os.makedirs(gptq_dir, exist_ok=True)
    safetensors.numpy.save_file(
        {k: np.ascontiguousarray(v) for k, v in tensors.items()},
        os.path.join(gptq_dir, "model.safetensors"))
    with open(os.path.join(base_dir, "config.json")) as f:
        cfg = json.load(f)
    cfg["quantization_config"] = {"quant_method": "gptq", "bits": 4,
                                  "group_size": group,
                                  "desc_act": act_order}
    with open(os.path.join(gptq_dir, "config.json"), "w") as f:
        json.dump(cfg, f)
    AutoTokenizer.from_pretrained(base_dir).save_pretrained(gptq_dir)

    twin_dir = str(tmp_path / "twin")
    model.load_state_dict({k: torch.from_numpy(np.ascontiguousarray(v))
                           for k, v in twin_sd.items()})
    model.save_pretrained(twin_dir, safe_serialization=True)
    AutoTokenizer.from_pretrained(base_dir).save_pretrained(twin_dir)
    return gptq_dir, twin_dir


def _assert_int4_tree_matches_fp(params_q, params_fp):
    """Every int4 leaf must dequantize BIT-EXACTLY to the fp twin's
    value (undoing the act-order row sort where present)."""
    from intellillm_tpu.layers.quantization import _dequant_int4

    def compare(a, t):
        if isinstance(a, dict) and "q4" in a:
            deq = np.asarray(_dequant_int4(
                {k: jnp.asarray(v) for k, v in a.items()
                 if k != "perm"}, jnp.float32))
            if "perm" in a:
                inv = np.empty_like(np.asarray(a["perm"]))
                inv[np.asarray(a["perm"])] = np.arange(len(inv))
                deq = deq[inv]
            np.testing.assert_array_equal(deq, np.asarray(t))
        elif isinstance(a, dict):
            for k in a:
                compare(a[k], t[k])
        elif isinstance(a, list):
            for x, y in zip(a, t):
                compare(x, y)
        elif a is not None:
            np.testing.assert_array_equal(np.asarray(a), np.asarray(t))

    compare(params_q, params_fp)


@pytest.mark.parametrize("act_order", [False, True])
def test_gptq_checkpoint_lossless(tiny_llama_dir, tmp_path,
                                  example_prompts, act_order):
    """GPTQ now loads LOSSLESSLY to the int4 device format (reference
    executes GPTQ exactly via gptq.py:114-212 + q_gemm.cu; here the same
    4-bit affine values reach the device unchanged, act-order handled by
    an input permutation). Weights must dequantize bit-exactly to the fp
    twin and first greedy tokens must agree (full-sequence equality is
    not asserted for the same fp32-accumulation-order reason as AWQ)."""
    from intellillm_tpu.config import ModelConfig
    from intellillm_tpu.models.model_loader import get_model

    gptq_dir, twin_dir = _gptqify_checkpoint(tiny_llama_dir, tmp_path,
                                             act_order=act_order)
    mc = ModelConfig(model=gptq_dir, dtype="float32")
    assert mc.quantization == "gptq"
    _, params_q = get_model(mc)
    _, params_fp = get_model(ModelConfig(model=twin_dir, dtype="float32"))
    _assert_int4_tree_matches_fp(params_q, params_fp)

    golden = _greedy(twin_dir, example_prompts)
    ours = _greedy(gptq_dir, example_prompts)
    for gold, o in zip(golden, ours):
        assert gold[0] == o[0]


def _squeezellmify_checkpoint(base_dir, tmp_path):
    """Convert a tiny fp llama checkpoint into (sqllm_dir, fp_twin_dir):
    per-channel 16-entry codebooks (channel quantiles — real checkpoints
    use k-means centroids; the format is identical) + nearest-index
    qweights, twin = exact LUT dequant."""
    import safetensors.numpy
    from transformers import AutoModelForCausalLM, AutoTokenizer

    model = AutoModelForCausalLM.from_pretrained(base_dir,
                                                 torch_dtype=torch.float32)
    sd = {k: v.numpy() for k, v in model.state_dict().items()}
    targets = [k for k in sd
               if k.endswith("_proj.weight") and "layers" in k]
    tensors = {k: v for k, v in sd.items() if k not in targets}
    twin_sd = dict(sd)
    for name in targets:
        w = sd[name].T.astype(np.float32)          # [in, out]
        in_, out = w.shape
        # Per-channel codebook: 16 quantiles of that channel's values.
        lut = np.quantile(w, np.linspace(0, 1, 16), axis=0).T  # [out, 16]
        lut = np.ascontiguousarray(lut.astype(np.float32))
        q = np.abs(w[:, :, None] - lut[None]).argmin(-1).astype(np.uint8)
        deq = np.take_along_axis(lut, q.transpose(1, 0), axis=1
                                 ).transpose(1, 0)  # lut[o, q[i,o]]
        prefix = name[:-len(".weight")]
        tensors[prefix + ".qweight"] = gptq_pack_rows(q)
        tensors[prefix + ".lookup_table"] = lut
        twin_sd[name] = np.ascontiguousarray(deq.T.astype(np.float32))

    sq_dir = str(tmp_path / "sqllm")
    os.makedirs(sq_dir, exist_ok=True)
    safetensors.numpy.save_file(
        {k: np.ascontiguousarray(v) for k, v in tensors.items()},
        os.path.join(sq_dir, "model.safetensors"))
    with open(os.path.join(base_dir, "config.json")) as f:
        cfg = json.load(f)
    cfg["quantization_config"] = {"quant_method": "squeezellm",
                                  "bits": 4}
    with open(os.path.join(sq_dir, "config.json"), "w") as f:
        json.dump(cfg, f)
    AutoTokenizer.from_pretrained(base_dir).save_pretrained(sq_dir)

    twin_dir = str(tmp_path / "sqllm-twin")
    model.load_state_dict({k: torch.from_numpy(np.ascontiguousarray(v))
                           for k, v in twin_sd.items()})
    model.save_pretrained(twin_dir, safe_serialization=True)
    AutoTokenizer.from_pretrained(base_dir).save_pretrained(twin_dir)
    return sq_dir, twin_dir


def test_squeezellm_checkpoint_lossless(tiny_llama_dir, tmp_path,
                                        example_prompts, caplog):
    """SqueezeLLM loads LOSSLESSLY to the {"q4lut","lut"} device format —
    the exact per-channel codebook executes at matmul time (reference
    squeezellm.py:122-127 + quant_cuda_kernel.cu), with NO int8
    requantization anywhere: every quantized leaf must dequantize
    bit-exactly to the fp twin and first greedy tokens must agree."""
    from intellillm_tpu.config import ModelConfig
    from intellillm_tpu.layers.quantization import _dequant_int4lut
    from intellillm_tpu.models.model_loader import get_model

    sq_dir, twin_dir = _squeezellmify_checkpoint(tiny_llama_dir, tmp_path)
    mc = ModelConfig(model=sq_dir, dtype="float32")
    assert mc.quantization == "squeezellm"
    import logging
    with caplog.at_level(logging.WARNING):
        _, params_q = get_model(mc)
    assert not [r for r in caplog.records
                if "requantiz" in r.getMessage()], (
        "squeezellm load emitted a requantization warning — the lossless "
        "path did not engage")
    _, params_fp = get_model(ModelConfig(model=twin_dir, dtype="float32"))

    def compare(a, t):
        if isinstance(a, dict) and "q4lut" in a:
            deq = np.asarray(_dequant_int4lut(
                {k: jnp.asarray(v) for k, v in a.items()}, jnp.float32))
            np.testing.assert_array_equal(deq, np.asarray(t))
        elif isinstance(a, dict):
            for k in a:
                compare(a[k], t[k])
        elif isinstance(a, list):
            for x, y in zip(a, t):
                compare(x, y)
        elif a is not None:
            np.testing.assert_array_equal(np.asarray(a), np.asarray(t))

    compare(params_q, params_fp)
    # Every projection really is LUT-format (nothing fell back to int8).
    n_lut = []

    def count(a):
        if isinstance(a, dict) and "q4lut" in a:
            n_lut.append(1)
        elif isinstance(a, dict):
            for v in a.values():
                count(v)
        elif isinstance(a, list):
            for v in a:
                count(v)

    count(params_q)
    assert len(n_lut) > 0

    golden = _greedy(twin_dir, example_prompts)
    ours = _greedy(sq_dir, example_prompts)
    for gold, o in zip(golden, ours):
        assert gold[0] == o[0]
