"""Grouped (ragged) MoE vs dense reference, and vs HF Mixtral block.

Reference test role: `tests/kernels/test_moe.py` (Triton fused_moe vs HF
MixtralSparseMoeBlock).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from intellillm_tpu.layers.moe import (moe_ffn, moe_ffn_dense,
                                       moe_ffn_grouped)


def _rand_weights(key, n, d, i, dtype=jnp.float32):
    ks = jax.random.split(key, 4)
    gate_w = jax.random.normal(ks[0], (d, n), jnp.float32) * 0.1
    w1 = jax.random.normal(ks[1], (n, d, i), dtype) * 0.1
    w2 = jax.random.normal(ks[2], (n, i, d), dtype) * 0.1
    w3 = jax.random.normal(ks[3], (n, d, i), dtype) * 0.1
    return gate_w, w1, w2, w3


@pytest.mark.parametrize("t", [1, 7, 64, 300])
@pytest.mark.parametrize("n,top_k", [(8, 2), (4, 1), (4, 4)])
@pytest.mark.parametrize("block", [8, 32])
def test_grouped_matches_dense(t, n, top_k, block):
    key = jax.random.PRNGKey(42)
    d, i = 16, 32
    gate_w, w1, w2, w3 = _rand_weights(key, n, d, i)
    x = jax.random.normal(jax.random.fold_in(key, 1), (t, d), jnp.float32)

    ref = moe_ffn_dense(x, gate_w, w1, w2, w3, top_k)
    out = moe_ffn_grouped(x, gate_w, w1, w2, w3, top_k, block=block)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_grouped_skewed_routing():
    """All tokens route to one expert — exercises group padding bounds."""
    n, top_k, d, i, t = 8, 2, 16, 32, 96
    key = jax.random.PRNGKey(0)
    gate_w, w1, w2, w3 = _rand_weights(key, n, d, i)
    # Bias the router so experts 3 and 5 dominate every token.
    gate_w = gate_w.at[:, 3].add(50.0).at[:, 5].add(40.0)
    x = jax.random.normal(jax.random.fold_in(key, 2), (t, d), jnp.float32)

    ref = moe_ffn_dense(x, gate_w, w1, w2, w3, top_k)
    out = moe_ffn_grouped(x, gate_w, w1, w2, w3, top_k, block=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_moe_matches_hf_mixtral_block():
    """Both paths vs the HF MixtralSparseMoeBlock golden (fp32)."""
    import torch
    from transformers import MixtralConfig
    from transformers.models.mixtral.modeling_mixtral import (
        MixtralSparseMoeBlock)

    d, i, n, top_k, t = 32, 64, 4, 2, 40
    cfg = MixtralConfig(hidden_size=d, intermediate_size=i,
                        num_local_experts=n, num_experts_per_tok=top_k)
    torch.manual_seed(0)
    blk = MixtralSparseMoeBlock(cfg).eval()
    x_t = torch.randn(1, t, d)
    with torch.no_grad():
        ref = blk(x_t)[0][0].numpy()

    gate_w = jnp.asarray(blk.gate.weight.detach().numpy().T)
    w1 = jnp.stack([jnp.asarray(e.w1.weight.detach().numpy().T)
                    for e in blk.experts])
    w2 = jnp.stack([jnp.asarray(e.w2.weight.detach().numpy().T)
                    for e in blk.experts])
    w3 = jnp.stack([jnp.asarray(e.w3.weight.detach().numpy().T)
                    for e in blk.experts])
    x = jnp.asarray(x_t[0].numpy())

    out_d = moe_ffn_dense(x, gate_w, w1, w2, w3, top_k)
    out_g = moe_ffn_grouped(x, gate_w, w1, w2, w3, top_k, block=16)
    np.testing.assert_allclose(np.asarray(out_d), ref, rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(out_g), ref, rtol=2e-4, atol=2e-4)


def test_dispatcher_picks_paths():
    """moe_ffn output is identical regardless of which path it picks."""
    n, top_k, d, i = 4, 2, 16, 32
    key = jax.random.PRNGKey(7)
    gate_w, w1, w2, w3 = _rand_weights(key, n, d, i)
    for t in (3, 600):
        x = jax.random.normal(jax.random.fold_in(key, t), (t, d),
                              jnp.float32)
        ref = moe_ffn_dense(x, gate_w, w1, w2, w3, top_k)
        out = moe_ffn(x, gate_w, w1, w2, w3, top_k, block=64)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)
