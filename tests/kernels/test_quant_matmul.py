"""Pallas int4 dequant-matmul kernel tests.

Reference role: `csrc/quantization/awq/gemm_kernels.cu` (awq_gemm) /
`gptq/q_gemm.cu` — the weight-stays-packed GEMM. On CPU the kernel runs
under TPU interpret mode (tests/kernels/conftest.py); on a real TPU the
memory test additionally proves the packed-bytes-only HBM claim that
VERDICT r3 flagged as unproven (int4's whole reason to exist).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from intellillm_tpu.layers.quantization import (_dequant_int4,
                                                quantize_int4)
from intellillm_tpu.ops.pallas.quant_matmul import (quant_matmul_int4,
                                                    supports)


def _pack(rng, in_, out, gs):
    w = quantize_int4(rng.standard_normal((in_, out)).astype(np.float32),
                      gs)
    return {k: jnp.asarray(v) for k, v in w.items()}


@pytest.mark.parametrize("in_,out,gs,b", [
    (256, 384, 32, 3),      # odd batch, 128-divisible out
    (64, 128, 16, 40),      # tiny model shapes
    (512, 640, 128, 8),     # group == K-tile unit
    (256, 256, 256, 5),     # one group for the whole input dim
])
def test_quant_matmul_matches_jnp_path(in_, out, gs, b):
    rng = np.random.default_rng(0)
    w = _pack(rng, in_, out, gs)
    assert supports(w)
    x = jnp.asarray(rng.standard_normal((b, in_)).astype(np.float32)
                    ).astype(jnp.bfloat16)
    ref = np.asarray(x @ _dequant_int4(w, x.dtype), np.float32)
    got = np.asarray(quant_matmul_int4(x, w), np.float32)
    # Same math, different accumulation order: bf16-scale tolerance.
    np.testing.assert_allclose(got, ref, atol=0.15, rtol=0.02)


def test_quant_matmul_3d_and_perm():
    """Leading batch dims + GPTQ act-order activation permutation."""
    rng = np.random.default_rng(1)
    in_, out, gs = 128, 256, 32
    w = _pack(rng, in_, out, gs)
    perm = rng.permutation(in_).astype(np.int32)
    wp = dict(w, perm=jnp.asarray(perm))
    x = jnp.asarray(rng.standard_normal((2, 3, in_)).astype(np.float32)
                    ).astype(jnp.bfloat16)
    xp = jnp.take(x, wp["perm"], axis=-1)
    ref = np.asarray(xp @ _dequant_int4(w, x.dtype), np.float32)
    got = np.asarray(quant_matmul_int4(x, wp), np.float32)
    assert got.shape == (2, 3, out)
    np.testing.assert_allclose(got, ref, atol=0.15, rtol=0.02)


def _pack_rows_int32(m: np.ndarray) -> np.ndarray:
    """8 sequential nibbles per int32 along the input dim (the GPTQ /
    SqueezeLLM checkpoint qweight layout)."""
    in_, out = m.shape
    packed = np.zeros((in_ // 8, out), np.int32)
    for j in range(8):
        packed |= m[j::8].astype(np.int32) << (4 * j)
    return packed


def _pack_lut(rng, in_, out):
    """Random SqueezeLLM-style weight: per-channel sorted 16-entry
    codebook + random indices."""
    q = rng.integers(0, 16, size=(in_, out)).astype(np.uint8)
    lut = np.sort(rng.standard_normal((16, out)).astype(np.float32),
                  axis=0)
    q4 = (q[0::2] | (q[1::2] << 4)).astype(np.uint8)
    return {"q4lut": jnp.asarray(q4), "lut": jnp.asarray(lut)}


@pytest.mark.parametrize("in_,out,b", [
    (256, 384, 3),
    (64, 128, 40),
    (300, 136, 5),          # non-128-divisible out, odd K padding
])
def test_quant_matmul_lut_matches_jnp_path(in_, out, b):
    """SqueezeLLM LUT kernel vs the exact jnp codebook-gather dequant
    (reference csrc/quantization/squeezellm/quant_cuda_kernel.cu role)."""
    from intellillm_tpu.layers.quantization import _dequant_int4lut
    from intellillm_tpu.ops.pallas.quant_matmul import (
        quant_matmul_int4_lut, supports_lut)
    rng = np.random.default_rng(6)
    w = _pack_lut(rng, in_, out)
    assert supports_lut(w)
    x = jnp.asarray(rng.standard_normal((b, in_)).astype(np.float32)
                    ).astype(jnp.bfloat16)
    ref = np.asarray(x @ _dequant_int4lut(w, x.dtype), np.float32)
    got = np.asarray(quant_matmul_int4_lut(x, w), np.float32)
    np.testing.assert_allclose(got, ref, atol=0.15, rtol=0.02)


def test_lut_dequant_is_exact():
    """The jnp LUT dequant reproduces the codebook values bit-exactly
    (no affine approximation anywhere in the path)."""
    from intellillm_tpu.layers.quantization import (_dequant_int4lut,
                                                    squeezellm_to_q4lut)
    rng = np.random.default_rng(7)
    in_, out = 32, 24
    q = rng.integers(0, 16, size=(in_, out)).astype(np.uint8)
    lut_ck = rng.standard_normal((out, 16)).astype(np.float32)  # [out,16]
    w = squeezellm_to_q4lut(_pack_rows_int32(q), lut_ck)
    deq = np.asarray(_dequant_int4lut(
        {k: jnp.asarray(v) for k, v in w.items()}, jnp.float32))
    ref = np.stack([lut_ck[o, q[:, o]] for o in range(out)], axis=1)
    np.testing.assert_array_equal(deq, ref)


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="memory_analysis buffer plan is TPU-specific")
def test_int4_stays_packed_in_hbm():
    """The compiled kernel must reserve no weight-sized temp: HBM holds
    the packed nibbles + group params only (VERDICT r3 item 3 — the
    XLA-path buffer plan reserves ~6x the packed bytes instead)."""
    rng = np.random.default_rng(2)
    in_, out, gs = 4096, 11008, 128
    w = _pack(rng, in_, out, gs)
    x = jnp.zeros((96, in_), jnp.bfloat16)
    c = jax.jit(quant_matmul_int4).lower(x, w).compile()
    ma = c.memory_analysis()
    packed = in_ // 2 * out
    dequant = in_ * out * 2                        # bf16 copy
    assert ma.temp_size_in_bytes < dequant // 4, ma.temp_size_in_bytes
    assert ma.argument_size_in_bytes < 2 * (packed + x.size * 2)
