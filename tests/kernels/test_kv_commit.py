"""Page-granular chunk commit vs the generic row-scatter reshape_and_cache.

Reference pattern: `tests/kernels/test_cache.py` (reshape_and_cache vs a
torch loop). The page gather→merge→scatter must produce bit-identical
pools to the row scatter for contiguous chunk commits, including
page-straddling starts, pad rows, and overshoot truncation.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from intellillm_tpu.ops.kv_cache import commit_staged_chunk, reshape_and_cache


def _reference(k_stage, v_stage, k_pool, v_pool, start, n_valid,
               block_tables, bs):
    b, c, hkv, d = k_stage.shape
    nb = k_pool.shape[0]
    slots = []
    for i in range(b):
        for t in range(c):
            if t < n_valid[i]:
                pos = start[i] + t
                blk = int(block_tables[i, pos // bs])
                slots.append(blk * bs + pos % bs)
            else:
                slots.append(nb * bs)  # OOB -> dropped
    slots = jnp.asarray(np.asarray(slots, np.int32))
    return reshape_and_cache(k_stage.reshape(b * c, hkv, d),
                             v_stage.reshape(b * c, hkv, d),
                             k_pool, v_pool, slots)


@pytest.mark.parametrize("start_offsets", [[0, 3, 15, 9]])
@pytest.mark.parametrize("chunk", [8, 16, 32])
@pytest.mark.parametrize("dtype", [np.float32, jnp.bfloat16])
def test_commit_staged_chunk_matches_row_scatter(start_offsets, chunk,
                                                 dtype):
    rng = np.random.default_rng(0)
    b, hkv, d, bs, nb, w = 4, 4, 32, 16, 64, 16
    k_pool = jnp.asarray(rng.normal(size=(nb, hkv, bs, d)), dtype=dtype)
    v_pool = jnp.asarray(rng.normal(size=(nb, hkv, bs, d)), dtype=dtype)
    k_stage = jnp.asarray(rng.normal(size=(b, chunk, hkv, d)), dtype=dtype)
    v_stage = jnp.asarray(rng.normal(size=(b, chunk, hkv, d)), dtype=dtype)
    tables = jnp.asarray(
        rng.permutation(nb)[:b * w].reshape(b, w).astype(np.int32))
    # Row 3 is a pad row (n_valid 0); row 2 truncates (overshoot).
    start = jnp.asarray(
        np.asarray([o + 32 * i for i, o in enumerate(start_offsets)],
                   np.int32))
    n_valid = jnp.asarray(np.asarray([chunk, chunk, chunk // 2, 0],
                                     np.int32))

    got_k, got_v = commit_staged_chunk(k_stage, v_stage, k_pool, v_pool,
                                       start, n_valid, tables)
    ref_k, ref_v = _reference(k_stage, v_stage, k_pool, v_pool,
                              np.asarray(start), np.asarray(n_valid),
                              np.asarray(tables), bs)
    np.testing.assert_array_equal(np.asarray(got_k, np.float32),
                                  np.asarray(ref_k, np.float32))
    np.testing.assert_array_equal(np.asarray(got_v, np.float32),
                                  np.asarray(ref_v, np.float32))


def test_commit_last_table_column_no_duplicate_write():
    """start in the LAST table column: the straddle candidate column is
    out of the table and must be dropped, not clipped onto the same page
    (a clipped duplicate would scatter the page twice with unspecified
    order)."""
    rng = np.random.default_rng(1)
    b, c, hkv, d, bs, nb, w = 1, 16, 2, 32, 16, 8, 4
    k_pool = jnp.zeros((nb, hkv, bs, d), jnp.float32)
    v_pool = jnp.zeros((nb, hkv, bs, d), jnp.float32)
    k_stage = jnp.asarray(rng.normal(size=(b, c, hkv, d)), jnp.float32)
    v_stage = jnp.asarray(rng.normal(size=(b, c, hkv, d)), jnp.float32)
    tables = jnp.asarray(np.asarray([[3, 5, 1, 7]], np.int32))
    start = jnp.asarray(np.asarray([48], np.int32))     # last column, o=0
    n_valid = jnp.asarray(np.asarray([c], np.int32))

    got_k, _ = commit_staged_chunk(k_stage, v_stage, k_pool, v_pool,
                                   start, n_valid, tables)
    got_k = np.asarray(got_k)
    for t in range(c):
        np.testing.assert_array_equal(got_k[7, :, t, :],
                                      np.asarray(k_stage)[0, t, :, :])
    # Nothing else was touched.
    untouched = [p for p in range(nb) if p != 7]
    assert np.all(got_k[untouched] == 0)


def test_commit_page_straddle_two_pages():
    """start%BS + C > BS forces writes across both candidate pages."""
    rng = np.random.default_rng(2)
    b, c, hkv, d, bs, nb, w = 2, 16, 2, 32, 16, 16, 4
    k_pool = jnp.zeros((nb, hkv, bs, d), jnp.float32)
    v_pool = jnp.zeros((nb, hkv, bs, d), jnp.float32)
    k_stage = jnp.asarray(rng.normal(size=(b, c, hkv, d)), jnp.float32)
    v_stage = jnp.asarray(rng.normal(size=(b, c, hkv, d)), jnp.float32)
    tables = jnp.asarray(
        rng.permutation(nb)[:b * w].reshape(b, w).astype(np.int32))
    start = jnp.asarray(np.asarray([8, 24], np.int32))
    n_valid = jnp.asarray(np.asarray([c, c], np.int32))

    got_k, _ = commit_staged_chunk(k_stage, v_stage, k_pool, v_pool,
                                   start, n_valid, tables)
    got_k = np.asarray(got_k)
    tables_np = np.asarray(tables)
    for i in range(b):
        s = int(start[i])
        for t in range(c):
            pos = s + t
            blk = tables_np[i, pos // bs]
            np.testing.assert_array_equal(
                got_k[blk, :, pos % bs, :],
                np.asarray(k_stage)[i, t, :, :])
