"""Pallas prefill flash-attention kernel vs the jnp reference over a
GQA × head-size × length × feature grid (reference pattern:
`tests/kernels/test_attention.py`). Runs under interpret mode on CPU
(see conftest.py) and natively on TPU."""
import jax.numpy as jnp
import numpy as np
import pytest

from intellillm_tpu.ops.attention import prefill_attention_reference

# On CPU the kernels run in TPU interpret mode (see conftest.py);
# the marker is kept as documentation of the native target.
requires_tpu = pytest.mark.kernel


def _run(hq, hkv, d, l, lens, sliding_window=None, use_alibi=False,
         dtype=np.float32, seed=0):
    from intellillm_tpu.layers.alibi import get_alibi_slopes
    from intellillm_tpu.ops.pallas.flash_attention import flash_attention

    rng = np.random.default_rng(seed)
    b = len(lens)
    q = jnp.asarray(rng.normal(size=(b, l, hq, d)).astype(dtype))
    k = jnp.asarray(rng.normal(size=(b, l, hkv, d)).astype(dtype))
    v = jnp.asarray(rng.normal(size=(b, l, hkv, d)).astype(dtype))
    ctx = jnp.asarray(np.asarray(lens, np.int32))
    slopes = (jnp.asarray(get_alibi_slopes(hq), jnp.float32)
              if use_alibi else None)
    scale = d**-0.5

    out_k = flash_attention(q, k, v, ctx, scale, sliding_window, slopes)
    out_r = prefill_attention_reference(q, k, v, ctx, scale, sliding_window,
                                        slopes)
    # Compare only valid rows: the reference computes (garbage) attention
    # for padded rows, the kernel zeros them; both are ignored downstream.
    for i, n in enumerate(lens):
        np.testing.assert_allclose(np.asarray(out_k)[i, :n],
                                   np.asarray(out_r)[i, :n],
                                   rtol=2e-2, atol=2e-2)


@requires_tpu
@pytest.mark.parametrize("hq,hkv", [(8, 8), (8, 2)])
@pytest.mark.parametrize("d", [64, 128])
def test_flash_attention_matches_reference(hq, hkv, d):
    _run(hq, hkv, d, 256, [256, 130, 17, 1])


@requires_tpu
def test_flash_attention_sliding_window():
    _run(8, 2, 128, 256, [256, 100], sliding_window=64)


@requires_tpu
def test_flash_attention_alibi():
    _run(8, 8, 128, 128, [128, 70], use_alibi=True)


@requires_tpu
def test_flash_attention_bf16():
    _run(8, 2, 128, 128, [128, 90], dtype=jnp.bfloat16)


@requires_tpu
def test_flash_attention_small_length():
    _run(4, 4, 128, 16, [16, 5])
