"""jnp attention-op correctness vs brute-force references (CPU-runnable)."""
import jax.numpy as jnp
import numpy as np
import pytest

from intellillm_tpu.ops.attention import (decode_attention_reference,
                                          merge_attention_parts,
                                          prefill_attention_reference,
                                          staged_decode_attention)
from intellillm_tpu.ops.kv_cache import (PAD_SLOT_ID, reshape_and_cache)


def brute_force_attn(q, k, v, scale, mask):
    # q [Hq, D], k/v [T, Hkv, D], mask [T]
    hq, d = q.shape
    t, hkv, _ = k.shape
    g = hq // hkv
    out = np.zeros((hq, d), np.float32)
    for h in range(hq):
        kh = k[:, h // g, :]
        vh = v[:, h // g, :]
        s = (kh @ q[h]) * scale
        s = np.where(mask, s, -np.inf)
        p = np.exp(s - s.max())
        p = p / p.sum()
        out[h] = p @ vh
    return out


def test_decode_attention_vs_brute_force():
    rng = np.random.default_rng(0)
    b, hq, hkv, d, nb, bs, w = 3, 4, 2, 16, 16, 4, 4
    k_cache = rng.normal(size=(nb, hkv, bs, d)).astype(np.float32)
    v_cache = rng.normal(size=(nb, hkv, bs, d)).astype(np.float32)
    q = rng.normal(size=(b, 1, hq, d)).astype(np.float32)
    tables = rng.permutation(nb)[:b * w].reshape(b, w).astype(np.int32)
    ctx = np.asarray([3, 9, 16], np.int32)

    out = decode_attention_reference(jnp.asarray(q), jnp.asarray(k_cache),
                                     jnp.asarray(v_cache),
                                     jnp.asarray(tables), jnp.asarray(ctx),
                                     scale=d**-0.5)
    out = np.asarray(out)

    for i in range(b):
        # Build the gathered context by walking the block table.
        ks, vs = [], []
        for blk in tables[i]:
            ks.append(k_cache[blk].transpose(1, 0, 2))  # [bs, Hkv, D]
            vs.append(v_cache[blk].transpose(1, 0, 2))
        kk = np.concatenate(ks, axis=0)
        vv = np.concatenate(vs, axis=0)
        mask = np.arange(w * bs) < ctx[i]
        expect = brute_force_attn(q[i, 0], kk, vv, d**-0.5, mask)
        np.testing.assert_allclose(out[i, 0], expect, rtol=1e-4, atol=1e-4)


def test_staged_merge_equals_unstaged():
    """pool-part + stage-part merged by lse == attention over the
    concatenated keys — the correctness core of fused multi-step decode."""
    rng = np.random.default_rng(1)
    b, hq, hkv, d, nb, bs, w, s = 2, 4, 2, 16, 16, 4, 4, 4
    k_cache = rng.normal(size=(nb, hkv, bs, d)).astype(np.float32)
    v_cache = rng.normal(size=(nb, hkv, bs, d)).astype(np.float32)
    q = rng.normal(size=(b, 1, hq, d)).astype(np.float32)
    tables = rng.permutation(nb)[:b * w].reshape(b, w).astype(np.int32)
    pool_ctx = np.asarray([5, 11], np.int32)
    k_stage = rng.normal(size=(b, s, hkv, d)).astype(np.float32)
    v_stage = rng.normal(size=(b, s, hkv, d)).astype(np.float32)
    stage_index = 2  # slots 0..2 valid
    scale = d**-0.5

    out_pool, lse_pool = decode_attention_reference(
        jnp.asarray(q), jnp.asarray(k_cache), jnp.asarray(v_cache),
        jnp.asarray(tables), jnp.asarray(pool_ctx), scale, return_lse=True)
    out_stage, lse_stage = staged_decode_attention(
        jnp.asarray(q), jnp.asarray(k_stage), jnp.asarray(v_stage),
        stage_index, scale)
    merged = np.asarray(merge_attention_parts(out_pool, lse_pool, out_stage,
                                              lse_stage))

    for i in range(b):
        ks, vs = [], []
        for blk in tables[i]:
            ks.append(k_cache[blk].transpose(1, 0, 2))
            vs.append(v_cache[blk].transpose(1, 0, 2))
        kk = np.concatenate(ks + [k_stage[i]], axis=0)
        vv = np.concatenate(vs + [v_stage[i]], axis=0)
        mask = np.concatenate([
            np.arange(w * bs) < pool_ctx[i],
            np.arange(s) <= stage_index,
        ])
        expect = brute_force_attn(q[i, 0], kk, vv, scale, mask)
        np.testing.assert_allclose(merged[i, 0], expect, rtol=1e-4,
                                   atol=1e-4)


def test_reshape_and_cache_pad_slots_dropped():
    """PAD_SLOT_ID rows must not corrupt the pool (regression: negative
    scatter indices wrap in XLA)."""
    nb, hkv, bs, d = 4, 2, 4, 8
    k_cache = jnp.zeros((nb, hkv, bs, d), jnp.float32)
    v_cache = jnp.zeros((nb, hkv, bs, d), jnp.float32)
    key = jnp.ones((2, hkv, d), jnp.float32)
    value = jnp.ones((2, hkv, d), jnp.float32) * 2
    slots = jnp.asarray([5, PAD_SLOT_ID], jnp.int32)
    k_cache, v_cache = reshape_and_cache(key, value, k_cache, v_cache, slots)
    k_np = np.array(k_cache)  # writable copy
    # slot 5 = block 1, offset 1 written; nothing else (esp. not the last
    # slot of the pool).
    assert (k_np[1, :, 1] == 1).all()
    k_np[1, :, 1] = 0
    assert (k_np == 0).all()


def test_prefill_attention_causality():
    rng = np.random.default_rng(2)
    b, l, h, d = 2, 8, 2, 16
    q = rng.normal(size=(b, l, h, d)).astype(np.float32)
    k = rng.normal(size=(b, l, h, d)).astype(np.float32)
    v = rng.normal(size=(b, l, h, d)).astype(np.float32)
    ctx = np.asarray([8, 5], np.int32)
    out = np.asarray(prefill_attention_reference(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), jnp.asarray(ctx),
        scale=d**-0.5))
    # Padded query rows (>= ctx) must at least be finite (they are ignored
    # downstream; NaNs would poison XLA's fused reductions).
    assert np.isfinite(out[1, 5:]).all()
    # Position 0 attends only to itself.
    for i in range(b):
        expect = brute_force_attn(q[i, 0], k[i][:1], v[i][:1], d**-0.5,
                                  np.asarray([True]))
        np.testing.assert_allclose(out[i, 0], expect, rtol=1e-4, atol=1e-4)
