"""Batched-LoRA BGMV kernel vs the gather-einsum `lora_delta` reference.
Tolerance-pinned (accumulation order differs between the VMEM-resident
kernel dots and XLA's batched einsums) EXCEPT slot-0 rows, which must be
exactly +0.0 on both paths (the pinned all-zero adapter). On TPU the
kernel compiles natively; on CPU it runs under Pallas TPU interpret mode
(tests/kernels/conftest.py)."""
import jax.numpy as jnp
import numpy as np
import pytest

requires_tpu = pytest.mark.kernel


def _reference_delta(x, a_stack, b_stack, row_slots):
    a_sel = a_stack[row_slots]
    b_sel = b_stack[row_slots]
    h = jnp.einsum("bld,bdr->blr", x, a_sel,
                   preferred_element_type=jnp.float32).astype(x.dtype)
    out = jnp.einsum("blr,bro->blo", h, b_sel,
                     preferred_element_type=jnp.float32)
    return out.astype(x.dtype)


def _stacks(rng, s, din, r, dout, dtype=np.float32):
    a = rng.normal(size=(s, din, r)).astype(dtype)
    b = rng.normal(size=(s, r, dout)).astype(dtype)
    a[0] = 0.0
    b[0] = 0.0
    return jnp.asarray(a), jnp.asarray(b)


@requires_tpu
@pytest.mark.parametrize("bsz,seq", [(8, 1), (3, 1), (8, 4)])
@pytest.mark.parametrize("rank", [8, 16])
def test_bgmv_matches_lora_delta(bsz, seq, rank):
    from intellillm_tpu.ops.pallas.bgmv import bgmv, bgmv_supported
    rng = np.random.default_rng(0)
    din, dout, s = 256, 128, 4
    a_stack, b_stack = _stacks(rng, s, din, rank, dout)
    x = jnp.asarray(rng.normal(size=(bsz, seq, din)).astype(np.float32))
    slots = jnp.asarray(rng.integers(0, s, bsz).astype(np.int32))
    assert bgmv_supported(x, a_stack, b_stack)

    out = bgmv(x, a_stack, b_stack, slots)
    ref = _reference_delta(x, a_stack, b_stack, slots)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


@requires_tpu
def test_bgmv_slot0_rows_exactly_zero():
    from intellillm_tpu.ops.pallas.bgmv import bgmv
    rng = np.random.default_rng(1)
    bsz, din, rank, dout, s = 8, 256, 16, 128, 3
    a_stack, b_stack = _stacks(rng, s, din, rank, dout)
    x = jnp.asarray(rng.normal(size=(bsz, 1, din)).astype(np.float32))
    slots = jnp.asarray(np.asarray([0, 1, 0, 2, 0, 0, 1, 0], np.int32))

    out = np.asarray(bgmv(x, a_stack, b_stack, slots))
    for i, slot in enumerate([0, 1, 0, 2, 0, 0, 1, 0]):
        if slot == 0:
            assert (out[i] == 0.0).all(), f"slot-0 row {i} not exact +0.0"
        else:
            assert np.abs(out[i]).max() > 0.0


@requires_tpu
def test_bgmv_bf16_activations():
    from intellillm_tpu.ops.pallas.bgmv import bgmv
    rng = np.random.default_rng(2)
    bsz, din, rank, dout, s = 8, 256, 16, 256, 4
    a_stack, b_stack = _stacks(rng, s, din, rank, dout)
    a_stack = a_stack.astype(jnp.bfloat16)
    b_stack = b_stack.astype(jnp.bfloat16)
    x = jnp.asarray(rng.normal(size=(bsz, 1, din)).astype(np.float32)
                    ).astype(jnp.bfloat16)
    slots = jnp.asarray(rng.integers(0, s, bsz).astype(np.int32))

    out = bgmv(x, a_stack, b_stack, slots)
    ref = _reference_delta(x, a_stack, b_stack, slots)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=2e-2, atol=2e-2)


def test_bgmv_supported_gates():
    """Pure-host gate logic — runs everywhere, no kernel launch."""
    from intellillm_tpu.ops.pallas.bgmv import bgmv_supported
    x = jnp.zeros((4, 1, 256), jnp.float32)
    ok_a = jnp.zeros((3, 256, 16), jnp.float32)
    ok_b = jnp.zeros((3, 16, 128), jnp.float32)
    assert bgmv_supported(x, ok_a, ok_b)
    # Misaligned model dims fall back to the gather-einsum path.
    assert not bgmv_supported(jnp.zeros((4, 1, 200), jnp.float32),
                              jnp.zeros((3, 200, 16), jnp.float32), ok_b)
    assert not bgmv_supported(x, ok_a, jnp.zeros((3, 16, 130),
                                                 jnp.float32))
    # Stacks beyond the VMEM residency budget fall back too.
    big_a = jnp.zeros((64, 4096, 64), jnp.float32)
    big_b = jnp.zeros((64, 64, 4096), jnp.float32)
    assert not bgmv_supported(jnp.zeros((4, 1, 4096), jnp.float32),
                              big_a, big_b)
