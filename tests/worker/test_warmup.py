"""Warm-up precompile smoke test.

`Worker.warm_up_model` (the CUDA-graph-capture analogue, reference
`model_runner.py:629-698`) normally runs only on TPU; here the backend
gate is bypassed so the exact warm-up call sequence — including the
fetch_indices (logits_processors) pytree variant and the fused-K program
— executes on CPU. Regressions in the warm-up argument plumbing
otherwise only surface as a swallowed best-effort warning on real
hardware.

Since the bucket-zoo deletion, default warm-up compiles the mixed
`(token_budget,)` family alone: exactly TWO executables (greedy +
sampled single-step decode at the top token bucket, narrowest width).
Everything else — the per-bucket sweep, fetch_indices pytree variant,
fused-K and pipelined-continuation programs — compiles lazily on first
use unless INTELLILLM_WARMUP_FULL=1.
"""
import jax
import pytest

from intellillm_tpu.config import (CacheConfig, ModelConfig, ParallelConfig,
                                   SchedulerConfig)
from intellillm_tpu.worker.worker import Worker


def _make_worker(num_decode_steps, max_model_len=128,
                 max_num_batched_tokens=2048, enable_chunked_prefill=False):
    from transformers import LlamaConfig

    # Smallest config that still exercises GQA: warm-up sweeps compile
    # dozens of executables, so per-compile cost dominates test time.
    hf = LlamaConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                     num_hidden_layers=1, num_attention_heads=4,
                     num_key_value_heads=2,
                     max_position_embeddings=max_model_len,
                     tie_word_embeddings=False)
    model_config = ModelConfig.from_hf_config(hf, dtype="float32",
                                              max_model_len=max_model_len,
                                              load_format="dummy")
    cache_config = CacheConfig(block_size=16,
                               num_device_blocks_override=64,
                               swap_space_gib=0.01)
    cache_config.num_device_blocks = 64
    cache_config.num_cpu_blocks = 4
    scheduler_config = SchedulerConfig(
        max_num_batched_tokens=max_num_batched_tokens,
        max_num_seqs=8,
        max_model_len=max_model_len,
        max_paddings=512,
        num_decode_steps=num_decode_steps,
        enable_chunked_prefill=enable_chunked_prefill)
    worker = Worker(model_config, ParallelConfig(), scheduler_config,
                    cache_config)
    worker.init_model()
    worker.load_model()
    worker.init_cache_engine(cache_config)
    return worker


@pytest.mark.parametrize("num_decode_steps", [1, 4])
def test_warm_up_default_is_two_mixed_executables(monkeypatch,
                                                  num_decode_steps):
    """Default warm-up compiles exactly the two steady-state sampler
    variants (greedy + sampled) of the mixed single-step program —
    regardless of --num-decode-steps (fused/continuation compile
    lazily). This is the <30s boot criterion's executable count."""
    worker = _make_worker(num_decode_steps)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    n = worker.warm_up_model()
    # None means the best-effort except path fired — in this controlled
    # environment that's a broken call sequence, not a hardware limit.
    assert n is not None, "warm-up fell back to lazy compilation"
    assert n == 2
    # Structured stats must agree with the return value (they feed the
    # boot timeline -> /health/detail -> bench warmup_compile field).
    assert worker.warmup_stats["executables"] == 2
    assert worker.warmup_stats["seconds"] > 0.0
    assert "error" not in worker.warmup_stats


@pytest.mark.parametrize("num_decode_steps", [1, 4])
def test_warm_up_count_invariant_under_kernel_flags(monkeypatch,
                                                    num_decode_steps):
    """Selecting the Pallas hot-path kernels (INTELLILLM_PALLAS_RAGGED /
    INTELLILLM_PALLAS_BGMV) must not change the default warm-up: the
    flags pick a code path at trace time INSIDE the two mixed
    executables, so the count stays exactly 2 and no extra program
    appears. (On this tiny model head size is 16, so the attention
    seam falls back to the reference body — which is precisely the
    invariance being pinned: flag state must not leak into bucketing.)
    The stats must also carry the trace-time kernel_selection snapshot
    that /health/detail and bench read."""
    monkeypatch.setenv("INTELLILLM_PALLAS_RAGGED", "1")
    monkeypatch.setenv("INTELLILLM_PALLAS_BGMV", "1")
    worker = _make_worker(num_decode_steps)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    n = worker.warm_up_model()
    assert n is not None, "warm-up fell back to lazy compilation"
    assert n == 2
    assert worker.warmup_stats["executables"] == 2
    sel = worker.warmup_stats["kernel_selection"]
    assert sel["ragged"] is True
    assert sel["bgmv"] is True


def test_warm_up_skipped_on_cpu():
    worker = _make_worker(1)
    assert worker.warm_up_model() is None
    assert worker.warmup_stats == {"executables": 0, "seconds": 0.0}


def test_warm_up_full_covers_every_token_bucket(monkeypatch):
    """INTELLILLM_WARMUP_FULL=1 sweeps every token bucket up to the
    budget plus the two narrowest widths, both sampler variants, the
    fetch_indices pytree variant, and the fused(+continuation) K-step
    programs — so nothing of the mixed family is left to compile
    mid-serving."""
    worker = _make_worker(num_decode_steps=4, max_model_len=128,
                          max_num_batched_tokens=64,
                          enable_chunked_prefill=True)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setenv("INTELLILLM_WARMUP_FULL", "1")
    n = worker.warm_up_model()
    assert n is not None
    from intellillm_tpu.utils import pad_to_bucket, pipeline_enabled_env
    buckets = worker.model_runner.mixed_token_buckets
    top = pad_to_bucket(64, buckets)
    batch_sizes = [bb for bb in buckets if bb <= top]
    assert len(batch_sizes) > 1   # full mode must sweep, not just top
    n_widths = len(buckets[:2])
    # Per (bucket, width, sampler-variant): single-step + fused +
    # (continuation when pipelining is enabled); plus ONE fetch_indices
    # variant (top bucket, narrowest width, greedy).
    per_combo = 3 if pipeline_enabled_env() else 2
    assert n == len(batch_sizes) * n_widths * 2 * per_combo + 1
    assert worker.warmup_stats["executables"] == n


def test_spec_worker_warmup_covers_teacher_and_draft(monkeypatch):
    """Speculative serving warm-up must compile the target's mixed pair,
    the draft model's mixed pair, and the K-ladder — for a fixed K
    (k_min == k_max) that is one teacher program plus one draft fused
    scan, six executables total in warmup_stats."""
    from transformers import LlamaConfig

    from intellillm_tpu.config import SpeculativeConfig
    from intellillm_tpu.worker.spec_decode.spec_worker import (
        SpecDecodeWorker)

    def mc(hidden, inter, layers):
        hf = LlamaConfig(vocab_size=128, hidden_size=hidden,
                         intermediate_size=inter, num_hidden_layers=layers,
                         num_attention_heads=4, num_key_value_heads=2,
                         max_position_embeddings=128,
                         tie_word_embeddings=False)
        return ModelConfig.from_hf_config(hf, dtype="float32",
                                          max_model_len=128,
                                          load_format="dummy")

    cache_config = CacheConfig(block_size=16,
                               num_device_blocks_override=64,
                               swap_space_gib=0.01)
    cache_config.num_device_blocks = 64
    cache_config.num_cpu_blocks = 4
    k_spec = 3
    scheduler_config = SchedulerConfig(max_num_batched_tokens=2048,
                                       max_num_seqs=8, max_model_len=128,
                                       max_paddings=512,
                                       num_decode_steps=k_spec + 1)
    spec = SpeculativeConfig(mc(32, 64, 1), k_spec)
    worker = SpecDecodeWorker(mc(32, 64, 1), ParallelConfig(),
                              scheduler_config, cache_config,
                              speculative_config=spec)
    worker.init_model()
    worker.load_model()
    worker.init_cache_engine(cache_config)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    n = worker.warm_up_model()
    assert n is not None, "spec warm-up fell back to lazy compilation"
    # 2 target mixed variants + 2 draft mixed variants + the K-ladder
    # (1 teacher + 1 draft fused per K rung; fixed K = one rung).
    assert n == 6
    assert worker.warmup_stats["executables"] == 6
    assert worker.warmup_stats["seconds"] > 0.0


def test_spec_worker_warmup_ladder_scales_with_band(monkeypatch):
    """An adaptive band [k_min, k_max] warms every rung: 4 generic mixed
    variants + 2 executables per K in the band, so no K transition can
    hit a cold compile mid-serving."""
    from transformers import LlamaConfig

    from intellillm_tpu.config import SpeculativeConfig
    from intellillm_tpu.worker.spec_decode.spec_worker import (
        SpecDecodeWorker)

    def mc(hidden, inter, layers):
        hf = LlamaConfig(vocab_size=128, hidden_size=hidden,
                         intermediate_size=inter, num_hidden_layers=layers,
                         num_attention_heads=4, num_key_value_heads=2,
                         max_position_embeddings=128,
                         tie_word_embeddings=False)
        return ModelConfig.from_hf_config(hf, dtype="float32",
                                          max_model_len=128,
                                          load_format="dummy")

    cache_config = CacheConfig(block_size=16,
                               num_device_blocks_override=64,
                               swap_space_gib=0.01)
    cache_config.num_device_blocks = 64
    cache_config.num_cpu_blocks = 4
    k_min, k_max = 2, 4
    scheduler_config = SchedulerConfig(max_num_batched_tokens=2048,
                                       max_num_seqs=8, max_model_len=128,
                                       max_paddings=512,
                                       num_decode_steps=k_max + 1)
    spec = SpeculativeConfig(mc(32, 64, 1), k_max, k_min=k_min,
                             k_max=k_max)
    worker = SpecDecodeWorker(mc(32, 64, 1), ParallelConfig(),
                              scheduler_config, cache_config,
                              speculative_config=spec)
    worker.init_model()
    worker.load_model()
    worker.init_cache_engine(cache_config)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    n = worker.warm_up_model()
    assert n is not None
    rungs = k_max - k_min + 1
    assert n == 4 + 2 * rungs
    assert worker.warmup_stats["executables"] == n
