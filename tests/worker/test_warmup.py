"""Warm-up precompile smoke test.

`Worker.warm_up_model` (the CUDA-graph-capture analogue, reference
`model_runner.py:629-698`) normally runs only on TPU; here the backend
gate is bypassed so the exact warm-up call sequence — including the
fetch_indices (logits_processors) pytree variant and the fused-K program
— executes on CPU. Regressions in the warm-up argument plumbing
otherwise only surface as a swallowed best-effort warning on real
hardware.
"""
import jax
import pytest

from intellillm_tpu.config import (CacheConfig, ModelConfig, ParallelConfig,
                                   SchedulerConfig)
from intellillm_tpu.worker.worker import Worker


def _make_worker(num_decode_steps, max_model_len=128):
    from transformers import LlamaConfig

    hf = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                     num_hidden_layers=2, num_attention_heads=4,
                     num_key_value_heads=2,
                     max_position_embeddings=max_model_len,
                     tie_word_embeddings=False)
    model_config = ModelConfig.from_hf_config(hf, dtype="float32",
                                              max_model_len=max_model_len,
                                              load_format="dummy")
    cache_config = CacheConfig(block_size=16,
                               num_device_blocks_override=64,
                               swap_space_gib=0.01)
    cache_config.num_device_blocks = 64
    cache_config.num_cpu_blocks = 4
    scheduler_config = SchedulerConfig(max_num_batched_tokens=2048,
                                       max_num_seqs=8,
                                       max_model_len=max_model_len,
                                       max_paddings=512,
                                       num_decode_steps=num_decode_steps)
    worker = Worker(model_config, ParallelConfig(), scheduler_config,
                    cache_config)
    worker.init_model()
    worker.load_model()
    worker.init_cache_engine(cache_config)
    return worker


@pytest.mark.parametrize("num_decode_steps", [1, 4])
def test_warm_up_compiles_all_variants(monkeypatch, num_decode_steps):
    worker = _make_worker(num_decode_steps)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    n = worker.warm_up_model()
    # None means the best-effort except path fired — in this controlled
    # environment that's a broken call sequence, not a hardware limit.
    assert n is not None, "warm-up fell back to lazy compilation"
    # Per warmed (width, sampler-variant): single-step + (fused +
    # pipelined continuation if K>1 and pipelining enabled); two sampler
    # variants (greedy fast path + sampled); plus one fetch_indices
    # variant on the first width (greedy only).
    from intellillm_tpu.utils import pipeline_enabled_env
    n_widths = len(worker.model_runner.block_width_buckets[:2])
    per_combo = ((3 if pipeline_enabled_env() else 2)
                 if num_decode_steps > 1 else 1)
    assert n == n_widths * 2 * per_combo + 1


def test_warm_up_skipped_on_cpu():
    worker = _make_worker(1)
    assert worker.warm_up_model() is None


def test_warm_up_full_covers_every_batch_bucket(monkeypatch):
    """INTELLILLM_WARMUP_FULL=1 sweeps every batch bucket AND every
    width bucket so no (bs, width) decode executable is left to compile
    mid-serving."""
    worker = _make_worker(num_decode_steps=4, max_model_len=1024)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setenv("INTELLILLM_WARMUP_FULL", "1")
    n = worker.warm_up_model()
    assert n is not None
    buckets = worker.model_runner.batch_buckets  # 1,2,4,8 for max_seqs=8
    # Full mode must cover ALL width buckets (>2 of them at mml=1024:
    # 16/32/64), two sampler variants, single+fused(+continuation when
    # pipelining is enabled) per combo.
    from intellillm_tpu.utils import pipeline_enabled_env
    n_widths = len(worker.model_runner.block_width_buckets)
    assert n_widths > 2
    per_combo = 3 if pipeline_enabled_env() else 2
    assert n == len(buckets) * n_widths * 2 * per_combo + 1


def test_spec_worker_warmup_covers_teacher_and_draft(monkeypatch):
    """Speculative serving warm-up must compile the draft model's decode
    programs and the teacher-forced verification program (and must NOT
    compile the pipelined-continuation program spec mode never uses)."""
    from transformers import LlamaConfig

    from intellillm_tpu.config import SpeculativeConfig
    from intellillm_tpu.worker.spec_decode.spec_worker import (
        SpecDecodeWorker)

    def mc(hidden, inter, layers):
        hf = LlamaConfig(vocab_size=128, hidden_size=hidden,
                         intermediate_size=inter, num_hidden_layers=layers,
                         num_attention_heads=4, num_key_value_heads=2,
                         max_position_embeddings=128,
                         tie_word_embeddings=False)
        return ModelConfig.from_hf_config(hf, dtype="float32",
                                          max_model_len=128,
                                          load_format="dummy")

    cache_config = CacheConfig(block_size=16,
                               num_device_blocks_override=64,
                               swap_space_gib=0.01)
    cache_config.num_device_blocks = 64
    cache_config.num_cpu_blocks = 4
    k_spec = 3
    scheduler_config = SchedulerConfig(max_num_batched_tokens=2048,
                                       max_num_seqs=8, max_model_len=128,
                                       max_paddings=512,
                                       num_decode_steps=k_spec + 1)
    spec = SpeculativeConfig(mc(32, 64, 1), k_spec)
    worker = SpecDecodeWorker(mc(64, 128, 2), ParallelConfig(),
                              scheduler_config, cache_config,
                              speculative_config=spec)
    worker.init_model()
    worker.load_model()
    worker.init_cache_engine(cache_config)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    n = worker.warm_up_model()
    assert n is not None, "spec warm-up fell back to lazy compilation"
    # target standard programs + the same set for the draft + 1 teacher;
    # no continuation programs in either pass.
    n_widths = len(worker.model_runner.block_width_buckets[:2])
    per_model = n_widths * 2 * 2 + 1   # single+fused, 2 sampler variants
    assert n == 2 * per_model + 1
