"""Rolling-window spec-decode accounting (worker/spec_decode/metrics.py)
— the object that replaced the worker's unbounded lifetime counters.
The controller steers on the WINDOW, so stale history must age out."""
import pytest

from intellillm_tpu.worker.spec_decode import metrics as spec_metrics
from intellillm_tpu.worker.spec_decode.metrics import SpecStats


def test_acceptance_rate_is_rolling_not_lifetime():
    stats = SpecStats(window_passes=4)
    # Four perfect passes...
    for _ in range(4):
        stats.record_pass(drafted=4, accepted=4, emitted=5, verified=5)
    assert stats.acceptance_rate() == 1.0
    # ...then four total-miss passes push them out of the window: the
    # rolling rate collapses to 0 even though the lifetime rate is 0.5.
    for _ in range(4):
        stats.record_pass(drafted=4, accepted=0, emitted=1, verified=5)
    assert stats.acceptance_rate() == 0.0
    assert stats.total_accepted == 16 and stats.total_drafted == 32


def test_cold_reads_are_safe():
    stats = SpecStats()
    assert stats.acceptance_rate() == 0.0
    assert stats.verify_waste_ratio() is None
    summary = stats.summary()
    assert summary["enabled"] is False
    assert summary["verify_waste_ratio"] is None


def test_verify_waste_ratio():
    stats = SpecStats()
    stats.record_pass(drafted=4, accepted=1, emitted=2, verified=5)
    # 5 verified positions, 2 emitted -> 60% of the verify work wasted.
    assert stats.verify_waste_ratio() == pytest.approx(0.6)


def test_per_request_accepted_pops_exactly_once():
    stats = SpecStats()
    stats.record_request_accepted("r1", 3)
    stats.record_request_accepted("r1", 2)
    stats.record_request_accepted("r2", 1)
    assert stats.pop_request_accepted("r1") == 5
    assert stats.pop_request_accepted("r1") is None
    assert stats.pop_request_accepted("unknown") is None
    assert stats.pop_request_accepted("r2") == 1


def test_per_request_map_is_bounded():
    stats = SpecStats()
    cap = spec_metrics._MAX_REQUEST_ENTRIES
    for i in range(cap + 10):
        stats.record_request_accepted(f"r{i}", 1)
    # Oldest evicted, newest retained.
    assert stats.pop_request_accepted("r0") is None
    assert stats.pop_request_accepted(f"r{cap + 9}") == 1


def test_configure_resets_window_for_a_fresh_engine():
    stats = SpecStats()
    stats.configure(k_min=1, k_max=4, k_init=2)
    stats.record_pass(drafted=4, accepted=0, emitted=1, verified=5)
    stats.record_request_accepted("stale", 1)
    # A rebuilt engine reconfigures the process-global singleton: the
    # rolling window and per-request map must start clean.
    stats.configure(k_min=1, k_max=4, k_init=3)
    assert stats.total_passes == 0
    assert stats.acceptance_rate() == 0.0
    assert stats.pop_request_accepted("stale") is None
    assert stats.current_k == 3


def test_reset_for_testing_allows_reregistration():
    # Unregisters the collector family; building fresh stats must not
    # raise a duplicate-registration error.
    spec_metrics.reset_for_testing()
    s1 = spec_metrics.get_spec_stats()
    spec_metrics.reset_for_testing()
    s2 = spec_metrics.get_spec_stats()
    assert s2 is not s1
    spec_metrics.reset_for_testing()
