"""MultiStepWorker equivalence test.

Reference: `tests/worker/spec_decode/test_multi_step_worker.py` — N fused
draft steps must produce exactly the tokens that N successive single-step
calls produce, and must not mutate the caller's sequence state.
"""
import copy

import numpy as np
import pytest

from intellillm_tpu.config import (CacheConfig, ModelConfig, ParallelConfig,
                                   SchedulerConfig)
from intellillm_tpu.sampling_params import SamplingParams
from intellillm_tpu.sequence import SequenceData, SequenceGroupMetadata
from intellillm_tpu.worker.spec_decode import MultiStepWorker

NUM_STEPS = 4
PROMPTS = [[5, 9, 2, 7, 1, 3], [11, 4, 8]]


def _make_worker():
    from transformers import LlamaConfig

    hf = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                     num_hidden_layers=2, num_attention_heads=4,
                     num_key_value_heads=2, max_position_embeddings=128,
                     tie_word_embeddings=False)
    model_config = ModelConfig.from_hf_config(hf, dtype="float32",
                                              max_model_len=128,
                                              load_format="dummy")
    cache_config = CacheConfig(block_size=16,
                               num_device_blocks_override=64,
                               swap_space_gib=0.01)
    cache_config.num_device_blocks = 64
    cache_config.num_cpu_blocks = 4
    scheduler_config = SchedulerConfig(max_num_batched_tokens=2048,
                                       max_num_seqs=8, max_model_len=128,
                                       max_paddings=512,
                                       num_decode_steps=NUM_STEPS)
    worker = MultiStepWorker(model_config, ParallelConfig(),
                             scheduler_config, cache_config)
    worker.init_model()
    worker.load_model()
    worker.init_cache_engine(cache_config)
    return worker


def _metadata(prompts_out, is_prompt):
    """prompts_out: list of (prompt_ids, output_ids). Prompt entries
    carry whole-prompt chunk metadata (token_chunk_size) — prompts only
    execute as chunk rows of the mixed dispatch now."""
    params = SamplingParams(temperature=0.0, max_tokens=32, ignore_eos=True)
    metas = []
    for i, (prompt, out) in enumerate(prompts_out):
        data = SequenceData(list(prompt))
        for t in out:
            data.append_token_id(t, 0.0)
        metas.append(SequenceGroupMetadata(
            request_id=str(i), is_prompt=is_prompt, seq_data={i: data},
            sampling_params=params,
            block_tables={i: [2 * i, 2 * i + 1]},
            token_chunk_size=len(prompt) if is_prompt else None))
    return metas


def _prefill(worker):
    outs = worker.execute_model(_metadata([(p, []) for p in PROMPTS], True),
                                {}, {}, {})
    return [out.samples[0].output_token for out in outs[0]]


def test_multi_step_matches_single_steps():
    worker = _make_worker()
    first = _prefill(worker)
    state = [(p, [t]) for p, t in zip(PROMPTS, first)]

    # N successive single-step decodes.
    single_state = copy.deepcopy(state)
    for _ in range(NUM_STEPS):
        outs = worker.execute_model(_metadata(single_state, False),
                                    {}, {}, {}, num_decode_steps=1)
        for i, group in enumerate(outs[0]):
            single_state[i][1].append(group.samples[0].output_token)

    # Fresh worker (fresh KV pool) replaying prefill, then one fused call.
    worker2 = _make_worker()
    first2 = _prefill(worker2)
    assert first2 == first
    metas = _metadata(state, False)
    outs = worker2.execute_model_multi_step(metas, {}, {}, {},
                                            num_steps=NUM_STEPS)
    assert len(outs) == NUM_STEPS
    multi_tokens = [[step[i].samples[0].output_token for step in outs]
                    for i in range(len(PROMPTS))]
    single_tokens = [s[1][1:] for s in single_state]
    assert multi_tokens == single_tokens

    # execute_model_multi_step appends into its internal copies only; the
    # caller's sequence state must be untouched.
    for i, meta in enumerate(metas):
        assert meta.seq_data[i].get_output_len() == 1


def test_multi_step_rejects_prompt_batches():
    worker = _make_worker()
    with pytest.raises(AssertionError, match="decode"):
        worker.execute_model_multi_step(
            _metadata([(p, []) for p in PROMPTS], True), {}, {}, {},
            num_steps=2)


def test_multi_step_asserts_kv_space():
    worker = _make_worker()
    first = _prefill(worker)
    state = [(p, [t]) for p, t in zip(PROMPTS, first)]
    metas = _metadata(state, False)
    with pytest.raises(AssertionError, match="block table"):
        worker.execute_model_multi_step(metas, {}, {}, {}, num_steps=30)
