"""Unit tests for the content-addressed KV handoff wire format
(worker/kv_transfer.py): handle identity, serialize/deserialize
roundtrip, and the validation that keeps a decode replica from
scattering a mismatched payload into its pool."""
import numpy as np
import pytest

from intellillm_tpu.affinity import affinity_key
from intellillm_tpu.worker.kv_transfer import (KVHandle, deserialize_handle,
                                               make_handle, resolve_dtype,
                                               serialize_handle)

GEOM = dict(block_size=8, num_layers=2, num_kv_heads=4, head_size=16,
            dtype="float32", num_blocks=3)


def _layers(handle, seed=0):
    rng = np.random.default_rng(seed)
    shape = (handle.num_blocks, handle.num_kv_heads, handle.block_size,
             handle.head_size)
    dtype = resolve_dtype(handle.dtype)
    return [(rng.standard_normal(shape).astype(dtype),
             rng.standard_normal(shape).astype(dtype))
            for _ in range(handle.num_layers)]


def test_make_handle_is_content_addressed():
    ids = list(range(24))
    handle = make_handle(ids, 0, **GEOM)
    assert handle.key == affinity_key(ids, 0)
    assert handle.num_tokens == 24
    # Same tokens under a different LoRA are a different prefix.
    assert make_handle(ids, 7, **GEOM).key != handle.key


def test_roundtrip_bit_exact():
    handle = make_handle(list(range(24)), 0, **GEOM)
    layers = _layers(handle)
    payload = serialize_handle(handle, layers)
    assert len(payload) > handle.payload_bytes()  # header + magic
    out_handle, out_layers = deserialize_handle(payload)
    assert out_handle == handle
    for (k, v), (ok, ov) in zip(layers, out_layers):
        np.testing.assert_array_equal(k, ok)
        np.testing.assert_array_equal(v, ov)


def test_roundtrip_bfloat16():
    handle = make_handle(list(range(16)), 0, **{**GEOM, "dtype": "bfloat16"})
    layers = _layers(handle)
    payload = serialize_handle(handle, layers)
    _, out_layers = deserialize_handle(payload)
    for (k, _), (ok, _) in zip(layers, out_layers):
        assert ok.dtype == resolve_dtype("bfloat16")
        np.testing.assert_array_equal(k.view(np.uint16),
                                      ok.view(np.uint16))


def test_serialize_rejects_wrong_shapes():
    handle = make_handle(list(range(24)), 0, **GEOM)
    layers = _layers(handle)
    with pytest.raises(ValueError, match="layers"):
        serialize_handle(handle, layers[:-1])
    bad = [(k[:, :1], v) for k, v in layers]
    with pytest.raises(ValueError, match="shape"):
        serialize_handle(handle, bad)


def test_deserialize_rejects_corruption():
    handle = make_handle(list(range(24)), 0, **GEOM)
    payload = serialize_handle(handle, _layers(handle))
    with pytest.raises(ValueError, match="magic"):
        deserialize_handle(b"XXXX" + payload[4:])
    with pytest.raises(ValueError, match="bytes"):
        deserialize_handle(payload[:-8])

    # A tampered key no longer matches the carried token ids: the
    # content address is recomputed, never trusted from the wire.
    tampered = KVHandle(key=handle.key ^ 1, token_ids=handle.token_ids,
                        lora_int_id=0, **GEOM)
    bad = serialize_handle(tampered, _layers(handle))
    with pytest.raises(ValueError, match="key"):
        deserialize_handle(bad)
