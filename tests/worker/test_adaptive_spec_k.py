"""Fake-clock unit tests for the SLO-adaptive speculative-K controller
(worker/spec_decode/adaptive.py). The controller is clock- and
signal-injectable, so every scenario here drives a synthetic clock and
synthetic pressure — no engine, no models, no sleeps."""
import pytest

from intellillm_tpu.worker.spec_decode.adaptive import AdaptiveKController


class FakeClock:
    def __init__(self, t0: float = 1000.0):
        self.t = t0

    def __call__(self) -> float:
        return self.t

    def advance(self, s: float) -> None:
        self.t += s


CLEAN = {"burn_firing": False, "tpot_p99_ms": None, "slo_tpot_ms": None,
         "acceptance": None}


def _controller(clock, signals, k_min=1, k_max=6, k_init=4, **kw):
    return AdaptiveKController(
        k_min, k_max, k_init=k_init, eval_interval_s=2.0,
        min_acceptance=0.4, grow_patience=3, now_fn=clock,
        signals_fn=lambda: signals[0], **kw)


def test_no_eval_inside_window():
    clock = FakeClock()
    signals = [dict(CLEAN, burn_firing=True)]
    c = _controller(clock, signals)
    # Sub-window ticks never evaluate: pressure is on but K holds.
    for _ in range(5):
        clock.advance(0.3)
        assert c.tick() == 4
    assert c.shrinks == 0


def test_shrinks_within_one_window_of_burn():
    clock = FakeClock()
    signals = [dict(CLEAN)]
    c = _controller(clock, signals)
    signals[0] = dict(CLEAN, burn_firing=True)
    clock.advance(2.1)
    assert c.tick() == 3, "burn signal must shrink K at the next window"
    assert c.shrinks == 1


def test_shrinks_on_tpot_over_slo_and_on_acceptance_floor():
    clock = FakeClock()
    signals = [dict(CLEAN, tpot_p99_ms=250.0, slo_tpot_ms=200.0)]
    c = _controller(clock, signals)
    clock.advance(2.1)
    assert c.tick() == 3
    signals[0] = dict(CLEAN, acceptance=0.1)
    clock.advance(2.1)
    assert c.tick() == 2
    # Acceptance above the floor is not pressure.
    signals[0] = dict(CLEAN, acceptance=0.9)
    clock.advance(2.1)
    assert c.tick() == 2


def test_grows_under_light_load_after_patience():
    clock = FakeClock()
    signals = [dict(CLEAN)]  # idle: no signals at all = clean window
    c = _controller(clock, signals, k_init=2)
    ks = []
    for _ in range(7):
        clock.advance(2.1)
        ks.append(c.tick())
    # Grows on the 3rd, then 6th clean window (patience resets per grow).
    assert ks == [2, 2, 3, 3, 3, 4, 4]
    assert c.grows == 2


def test_hysteresis_one_clean_window_never_undoes_a_shrink():
    clock = FakeClock()
    signals = [dict(CLEAN, burn_firing=True)]
    c = _controller(clock, signals)
    clock.advance(2.1)
    assert c.tick() == 3
    # One clean window: K must NOT bounce back.
    signals[0] = dict(CLEAN)
    clock.advance(2.1)
    assert c.tick() == 3
    # A new burn resets the good-window streak...
    signals[0] = dict(CLEAN, burn_firing=True)
    clock.advance(2.1)
    assert c.tick() == 2
    # ...so recovery needs the FULL patience again.
    signals[0] = dict(CLEAN)
    for expected in (2, 2, 3):
        clock.advance(2.1)
        assert c.tick() == expected


def test_never_leaves_band():
    clock = FakeClock()
    signals = [dict(CLEAN, burn_firing=True)]
    c = _controller(clock, signals, k_min=2, k_max=4, k_init=3)
    for _ in range(10):
        clock.advance(2.1)
        assert 2 <= c.tick() <= 4
    assert c.k == 2  # pinned at the floor, never below
    signals[0] = dict(CLEAN)
    for _ in range(20):
        clock.advance(2.1)
        assert 2 <= c.tick() <= 4
    assert c.k == 4  # pinned at the ceiling, never above


def test_k_init_clamped_and_band_asserted():
    clock = FakeClock()
    signals = [dict(CLEAN)]
    c = _controller(clock, signals, k_min=2, k_max=4, k_init=9)
    assert c.k == 4
    with pytest.raises(AssertionError):
        AdaptiveKController(5, 2, now_fn=clock,
                            signals_fn=lambda: signals[0])


def test_snapshot_carries_state_and_last_signals():
    clock = FakeClock()
    signals = [dict(CLEAN, acceptance=0.05)]
    c = _controller(clock, signals)
    clock.advance(2.1)
    c.tick()
    snap = c.snapshot()
    assert snap["k"] == 3
    assert snap["shrinks"] == 1
    assert snap["last_signals"]["acceptance"] == 0.05
    assert snap["k_min"] == 1 and snap["k_max"] == 6
