"""Adaptive-K compile-ladder guarantee: after the first pass over the
configured [k_min, k_max] band, runtime K transitions dispatch only
warm executables — zero new compiles in the CompileTracker.

Boot warm-up calls the jitted programs directly (bypassing the
tracker), so the tracker's first-seen accounting registers each
(program, shape-key) on its FIRST runtime dispatch. The assertion is
therefore two-cycle: sweep every K in the band once (registers every
key), snapshot, sweep again — the second sweep must add nothing.
"""
import pytest
import torch

from intellillm_tpu import LLM, SamplingParams
from intellillm_tpu.obs import get_compile_tracker


@pytest.fixture(scope="module")
def draft_llama_dir(tmp_path_factory):
    from tests.conftest import _build_word_tokenizer
    from transformers import LlamaConfig, LlamaForCausalLM

    d = str(tmp_path_factory.mktemp("tiny-llama-draft-ladder"))
    _, vocab_size = _build_word_tokenizer(d)
    torch.manual_seed(7)
    model = LlamaForCausalLM(LlamaConfig(
        vocab_size=vocab_size, hidden_size=32, intermediate_size=64,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=128, tie_word_embeddings=False,
        pad_token_id=0, bos_token_id=1, eos_token_id=1,
        torch_dtype=torch.float32))
    model.eval()
    model.save_pretrained(d, safe_serialization=True)
    return d


def test_k_transitions_reuse_warm_executables(tiny_llama_dir,
                                              draft_llama_dir,
                                              monkeypatch):
    # Run the whole ladder with the Pallas hot-path kernels selected:
    # the INTELLILLM_PALLAS_* flags are trace-time choices inside the
    # same programs, so the K-ladder executable count and the warm-reuse
    # guarantee must be identical to the flags-off default.
    monkeypatch.setenv("INTELLILLM_PALLAS_RAGGED", "1")
    monkeypatch.setenv("INTELLILLM_PALLAS_BGMV", "1")
    k_min, k_max = 1, 3
    llm = LLM(model=tiny_llama_dir, dtype="float32",
              num_device_blocks_override=128, max_model_len=128,
              max_num_seqs=8, max_paddings=512, swap_space=0.01,
              speculative_model=draft_llama_dir,
              num_speculative_tokens=2, spec_k_min=k_min,
              spec_k_max=k_max)
    engine = llm.llm_engine
    worker = engine.worker

    # Drive K deterministically: each engine step consumes the next K
    # from the schedule (two full sweeps of the band), pinning the
    # controller out of the loop.
    schedule = []

    def scripted_steps():
        if schedule:
            worker.k_spec = schedule.pop(0)
        return worker.k_spec + 1

    monkeypatch.setattr(worker, "adaptive_num_decode_steps",
                        scripted_steps)

    engine.add_request(
        "0", "the cat runs fast and the dog",
        SamplingParams(temperature=0.0, max_tokens=64, ignore_eos=True))

    def sweep(ks):
        """One engine step per K; returns when the schedule drained."""
        schedule.extend(ks)
        while schedule and engine.has_unfinished_requests():
            engine.step()
        assert not schedule, "request finished before the sweep completed"

    band = list(range(k_min, k_max + 1))
    # Cycle 1: first runtime dispatch at every K registers its
    # (program, key) pairs with the tracker.
    sweep(band + band[::-1])
    snap1 = get_compile_tracker().snapshot()

    # Cycle 2: every K transition again — all keys must be warm now.
    sweep(band[::-1] + band)
    snap2 = get_compile_tracker().snapshot()

    assert snap2["compiles"] == snap1["compiles"], (
        "a runtime K transition triggered a fresh compile: "
        f"{snap1['compiles']} -> {snap2['compiles']} — the K-ladder "
        "warm-up (or shape bucketing) no longer covers the band")
    # The second cycle really dispatched (cache hits grew).
    assert (sum(snap2["cache_hits"].values())
            > sum(snap1["cache_hits"].values()))
