"""Interpret-mode parity: EVERY Pallas kernel against its jnp reference.

One suite, one shape apiece — the deep per-kernel grids live in
tests/kernels/; this file is the cheap cross-cutting safety net that a
CPU-tier CI job can run (and that skips with an explicit reason on jax
versions without `force_tpu_interpret_mode` — see conftest.py). If a
kernel gains a reference-contract change, it must show up here AND in
docs/kernels.md.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from intellillm_tpu.ops.attention import (decode_attention_reference,
                                          prefill_attention_reference)
from intellillm_tpu.ops.ragged_attention import (
    ragged_fused_attention_reference)

TOL = dict(rtol=2e-3, atol=2e-3)


def test_flash_attention_parity(tpu_interpret):
    from intellillm_tpu.ops.pallas.flash_attention import flash_attention
    rng = np.random.default_rng(0)
    b, l, hq, hkv, d = 2, 64, 4, 2, 128
    q = jnp.asarray(rng.normal(size=(b, l, hq, d)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(b, l, hkv, d)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(b, l, hkv, d)).astype(np.float32))
    ctx = jnp.asarray(np.asarray([l, 37], np.int32))
    out = flash_attention(q, k, v, ctx, d**-0.5)
    ref = prefill_attention_reference(q, k, v, ctx, d**-0.5, None, None)
    # Compare valid rows only: the kernel zeroes rows past context_lens,
    # the reference's are unspecified.
    for i, c in enumerate([l, 37]):
        np.testing.assert_allclose(np.asarray(out)[i, :c],
                                   np.asarray(ref)[i, :c], **TOL)


def test_paged_attention_parity(tpu_interpret):
    from intellillm_tpu.ops.pallas.paged_attention import paged_attention
    rng = np.random.default_rng(1)
    b, hq, hkv, d, nb, bs, w = 4, 8, 2, 128, 64, 16, 8
    k_cache = jnp.asarray(
        rng.normal(size=(nb, hkv, bs, d)).astype(np.float32))
    v_cache = jnp.asarray(
        rng.normal(size=(nb, hkv, bs, d)).astype(np.float32))
    q = jnp.asarray(rng.normal(size=(b, 1, hq, d)).astype(np.float32))
    tables = jnp.asarray(
        rng.permutation(nb)[:b * w].reshape(b, w).astype(np.int32))
    ctx = jnp.asarray(np.asarray([1, 17, 63, 128], np.int32))
    out, lse = paged_attention(q, k_cache, v_cache, tables, ctx, d**-0.5,
                               return_lse=True)
    ref, ref_lse = decode_attention_reference(q, k_cache, v_cache, tables,
                                              ctx, d**-0.5,
                                              return_lse=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), **TOL)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse),
                               **TOL)


def test_ragged_fused_parity(tpu_interpret):
    from intellillm_tpu.ops.pallas.ragged_paged_attention import (
        ragged_paged_attention)
    rng = np.random.default_rng(2)
    b, hq, hkv, d, nb, bs, w = 6, 4, 2, 128, 64, 16, 8
    k_cache = jnp.asarray(
        rng.normal(size=(nb, hkv, bs, d)).astype(np.float32))
    v_cache = jnp.asarray(
        rng.normal(size=(nb, hkv, bs, d)).astype(np.float32))
    q = jnp.asarray(rng.normal(size=(b, 1, hq, d)).astype(np.float32))
    k_new = jnp.asarray(rng.normal(size=(b, hkv, d)).astype(np.float32))
    v_new = jnp.asarray(rng.normal(size=(b, hkv, d)).astype(np.float32))
    tables = rng.permutation(nb)[:b * w].reshape(b, w).astype(np.int32)
    # A chunk run: rows 2..4 are one sequence at positions 29/30/31.
    tables[3] = tables[2]
    tables[4] = tables[2]
    ctx_lens = [1, 40, 30, 31, 32, 0]
    slots = []
    for i, c in enumerate(ctx_lens):
        if c == 0:
            slots.append(-1)
        else:
            blk = int(tables[i, (c - 1) // bs])
            slots.append(blk * bs + (c - 1) % bs)
    tables = jnp.asarray(tables)
    slots = jnp.asarray(np.asarray(slots, np.int32))
    ctx = jnp.asarray(np.asarray(ctx_lens, np.int32))

    out, kc, vc = ragged_paged_attention(q, k_new, v_new, k_cache,
                                         v_cache, slots, tables, ctx,
                                         d**-0.5)
    ref, kr, vr = ragged_fused_attention_reference(q, k_new, v_new,
                                                   k_cache, v_cache,
                                                   slots, tables, ctx,
                                                   d**-0.5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), **TOL)
    np.testing.assert_array_equal(np.asarray(kc), np.asarray(kr))
    np.testing.assert_array_equal(np.asarray(vc), np.asarray(vr))


def test_bgmv_parity(tpu_interpret):
    from intellillm_tpu.ops.pallas.bgmv import bgmv, bgmv_supported
    rng = np.random.default_rng(3)
    bsz, din, rank, dout, s = 8, 256, 16, 128, 4
    a = rng.normal(size=(s, din, rank)).astype(np.float32)
    b = rng.normal(size=(s, rank, dout)).astype(np.float32)
    a[0] = 0.0
    b[0] = 0.0
    a_stack, b_stack = jnp.asarray(a), jnp.asarray(b)
    x = jnp.asarray(rng.normal(size=(bsz, 1, din)).astype(np.float32))
    slots = jnp.asarray(np.asarray([0, 1, 2, 3, 0, 2, 1, 0], np.int32))
    assert bgmv_supported(x, a_stack, b_stack)

    out = bgmv(x, a_stack, b_stack, slots)
    a_sel, b_sel = a_stack[slots], b_stack[slots]
    h = jnp.einsum("bld,bdr->blr", x, a_sel,
                   preferred_element_type=jnp.float32).astype(x.dtype)
    ref = jnp.einsum("blr,bro->blo", h, b_sel,
                     preferred_element_type=jnp.float32).astype(x.dtype)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    assert (np.asarray(out)[np.asarray(slots) == 0] == 0.0).all()


def test_quant_matmul_parity(tpu_interpret):
    from intellillm_tpu.layers.quantization import (_dequant_int4,
                                                    quantize_int4)
    from intellillm_tpu.ops.pallas.quant_matmul import (quant_matmul_int4,
                                                        supports)
    rng = np.random.default_rng(4)
    in_, out_, gs, bsz = 256, 384, 32, 3
    w = {k: jnp.asarray(v) for k, v in quantize_int4(
        rng.standard_normal((in_, out_)).astype(np.float32), gs).items()}
    assert supports(w)
    x = jnp.asarray(rng.standard_normal((bsz, in_)).astype(np.float32)
                    ).astype(jnp.bfloat16)
    ref = np.asarray(x @ _dequant_int4(w, x.dtype), np.float32)
    got = np.asarray(quant_matmul_int4(x, w), np.float32)
    np.testing.assert_allclose(got, ref, atol=0.15, rtol=0.02)
