"""Fixtures for the ops-layer tests.

Unlike tests/kernels/ (whose autouse fixture gates the WHOLE directory
on interpret mode), only the parity suite here needs to execute Mosaic
kernels — the reference-seam tests run everywhere on plain CPU — so the
interpret gate is an opt-in fixture, with the same guarded-skip pattern
as `test_sp_prefill_bench_smoke`.
"""
import jax
import pytest
from jax.experimental.pallas import tpu as pltpu


@pytest.fixture
def tpu_interpret():
    """Run the test body with Pallas TPU kernels executable: natively on
    TPU, under `force_tpu_interpret_mode` on CPU, guarded-skip on jax
    versions that predate the interpret API."""
    if jax.default_backend() == "tpu":
        yield
    elif not hasattr(pltpu, "force_tpu_interpret_mode"):
        pytest.skip("pallas force_tpu_interpret_mode is absent on this "
                    "jax version; kernel parity needs TPU or interpret "
                    "mode")
    else:
        with pltpu.force_tpu_interpret_mode():
            yield
