"""Real-checkpoint load validation on TPU (VERDICT r4 item 8).

Loads a REAL (safetensors, non-dummy) checkpoint through the full
quantize-on-load path on the TPU backend, records wall-clock load time,
and proves first-token correctness by comparing the greedy stream
against the same checkpoint served on CPU (the CPU path is golden-tested
against HF transformers).

The checkpoint is built locally (no network): examples/make_tiny_model.py
writes a genuine safetensors checkpoint + tokenizer, so the exercised
surface is hf_model_weights_iterator -> load_linear -> quantize_int8 ->
shard_params -> device placement — everything a real 7B load runs, at
tiny scale.

Usage:  python benchmarks/real_checkpoint_tpu.py [--model DIR]
Prints one JSON line with load/generate timings and the match verdict.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.join(os.path.dirname(__file__), "..")
sys.path.insert(0, REPO)

_CHILD = r"""
import json, sys, time
model_dir, quant = sys.argv[1], sys.argv[2]
t0 = time.time()
from intellillm_tpu import LLM, SamplingParams
t_import = time.time() - t0
t0 = time.time()
llm = LLM(model=model_dir, dtype="bfloat16",
          quantization=None if quant == "none" else quant,
          num_device_blocks_override=128, max_model_len=128,
          max_num_seqs=8, swap_space=0.01)
t_load = time.time() - t0
prompts = ["hello my name is", "the capital of france is"]
t0 = time.time()
outs = llm.generate(prompts, SamplingParams(temperature=0.0,
                                            max_tokens=12))
t_gen = time.time() - t0
import jax
print(json.dumps({
    "backend": jax.devices()[0].platform,
    "import_s": round(t_import, 2), "load_s": round(t_load, 2),
    "generate_s": round(t_gen, 2),
    "tokens": [list(o.outputs[0].token_ids) for o in outs],
    "texts": [o.outputs[0].text for o in outs],
}))
"""


def run_backend(model_dir: str, quant: str, cpu: bool) -> dict:
    env = dict(os.environ)
    if cpu:
        env["PALLAS_AXON_POOL_IPS"] = ""
        env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run([sys.executable, "-c", _CHILD, model_dir, quant],
                       capture_output=True, text=True, env=env,
                       timeout=1200)
    if r.returncode != 0:
        return {"error": r.stderr.strip().splitlines()[-1:][0]
                if r.stderr.strip() else "unknown"}
    return json.loads(r.stdout.strip().splitlines()[-1])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="/tmp/tiny-llama-real")
    ap.add_argument("--quantization", default="int8")
    args = ap.parse_args()

    if not os.path.isdir(args.model):
        subprocess.run([sys.executable,
                        os.path.join(REPO, "examples", "make_tiny_model.py"),
                        "--arch", "llama", "--out", args.model],
                       check=True)

    cpu = run_backend(args.model, args.quantization, cpu=True)
    tpu = run_backend(args.model, args.quantization, cpu=False)
    match = (("tokens" in cpu and "tokens" in tpu)
             and all(c[0] == t[0] for c, t in zip(cpu["tokens"],
                                                  tpu["tokens"])))
    print(json.dumps({
        "metric": "real-checkpoint int8 load on TPU",
        "cpu": cpu, "tpu": tpu,
        "first_token_match": match,
    }))
    return 0 if match else 1


if __name__ == "__main__":
    sys.exit(main())
