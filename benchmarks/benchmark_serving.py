"""Online serving benchmark: Poisson arrivals against a live server.

Role parity: reference `benchmarks/benchmark_serving.py` (async request
generator with exponential inter-arrival gaps, per-request latency + TTFT
percentiles, request/token throughput). Start the server first, e.g.:

    python -m intellillm_tpu.entrypoints.openai.api_server --model ... &
    python benchmarks/benchmark_serving.py --backend openai \
        --model <model> --num-prompts 100 --request-rate 4

    python -m intellillm_tpu.entrypoints.api_server --model ... &
    python benchmarks/benchmark_serving.py --backend generate ...
"""
from __future__ import annotations

import argparse
import asyncio
import json
import os
import random
import sys
import time
from typing import List, Optional, Tuple

import aiohttp
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from benchmarks.common import percentiles, sample_requests  # noqa: E402

# (prompt, prompt_len, output_len) → (e2e_latency, ttft, n_chunks)
REQUEST_LATENCIES: List[Tuple[int, int, float, float, int]] = []


async def get_request(requests, request_rate: float):
    for req in requests:
        yield req
        if request_rate == float("inf"):
            continue
        await asyncio.sleep(np.random.exponential(1.0 / request_rate))


async def send_request(session: aiohttp.ClientSession, backend: str,
                       api_url: str, model: str, prompt: str,
                       prompt_len: int, output_len: int,
                       best_of: int) -> None:
    if backend == "openai":
        payload = {
            "model": model,
            "prompt": prompt,
            "max_tokens": output_len,
            "temperature": 0.0 if best_of > 1 else 1.0,
            "best_of": best_of,
            "ignore_eos": True,
            "stream": True,
        }
    else:  # simple /generate server
        payload = {
            "prompt": prompt,
            "max_tokens": output_len,
            "temperature": 0.0 if best_of > 1 else 1.0,
            "best_of": best_of,
            "ignore_eos": True,
            "stream": True,
        }
    start = time.perf_counter()
    ttft = None
    n_chunks = 0
    async with session.post(api_url, json=payload) as resp:
        resp.raise_for_status()
        async for line in resp.content:
            if not line.strip():
                continue
            if ttft is None:
                ttft = time.perf_counter() - start
            n_chunks += 1
    latency = time.perf_counter() - start
    REQUEST_LATENCIES.append((prompt_len, output_len, latency, ttft or
                              latency, n_chunks))


async def benchmark(args, requests) -> float:
    api_url = (f"http://{args.host}:{args.port}/v1/completions"
               if args.backend == "openai" else
               f"http://{args.host}:{args.port}/generate")
    conn = aiohttp.TCPConnector(limit=0)
    timeout = aiohttp.ClientTimeout(total=6 * 3600)
    start = time.perf_counter()
    async with aiohttp.ClientSession(connector=conn,
                                     timeout=timeout) as session:
        tasks = []
        async for prompt, prompt_len, output_len in get_request(
                requests, args.request_rate):
            tasks.append(asyncio.create_task(
                send_request(session, args.backend, api_url, args.model,
                             prompt, prompt_len, output_len, args.best_of)))
        await asyncio.gather(*tasks)
    return time.perf_counter() - start


def main(args):
    random.seed(args.seed)
    np.random.seed(args.seed)

    from transformers import AutoTokenizer
    tokenizer = AutoTokenizer.from_pretrained(args.tokenizer or args.model)

    raw = sample_requests(args.dataset, args.num_prompts, tokenizer,
                          args.input_len, args.output_len, len(tokenizer),
                          args.seed)
    requests = []
    for prompt_ids, output_len in raw:
        prompt = tokenizer.decode(prompt_ids, skip_special_tokens=True)
        requests.append((prompt, len(prompt_ids), output_len))

    elapsed = asyncio.run(benchmark(args, requests))

    total_output = sum(o for _, _, o in requests)
    lat = [r[2] for r in REQUEST_LATENCIES]
    ttft = [r[3] for r in REQUEST_LATENCIES]
    per_tok = [r[2] / max(r[1], 1) for r in REQUEST_LATENCIES]

    print(f"Completed {len(REQUEST_LATENCIES)}/{len(requests)} requests "
          f"in {elapsed:.2f} s")
    print(f"Request throughput: {len(REQUEST_LATENCIES) / elapsed:.2f} "
          "requests/s")
    print(f"Output token throughput: {total_output / elapsed:.1f} tok/s")
    print(f"Mean latency: {np.mean(lat):.3f} s  "
          + "  ".join(f"{k}={v:.3f}s"
                      for k, v in percentiles(lat).items()))
    print(f"Mean TTFT: {np.mean(ttft) * 1e3:.1f} ms  "
          + "  ".join(f"{k}={v * 1e3:.1f}ms"
                      for k, v in percentiles(ttft).items()))
    print(f"Mean per-output-token latency: "
          f"{np.mean(per_tok) * 1e3:.1f} ms/tok")


if __name__ == "__main__":
    parser = argparse.ArgumentParser(
        description="Benchmark online serving throughput/latency.")
    parser.add_argument("--backend", type=str, default="openai",
                        choices=["openai", "generate"])
    parser.add_argument("--host", type=str, default="localhost")
    parser.add_argument("--port", type=int, default=8000)
    parser.add_argument("--model", type=str, required=True,
                        help="model name for the openai endpoint / "
                        "tokenizer source")
    parser.add_argument("--tokenizer", type=str, default=None)
    parser.add_argument("--dataset", type=str, default=None)
    parser.add_argument("--num-prompts", type=int, default=100)
    parser.add_argument("--input-len", type=int, default=128)
    parser.add_argument("--output-len", type=int, default=128)
    parser.add_argument("--best-of", type=int, default=1)
    parser.add_argument("--request-rate", type=float, default=float("inf"),
                        help="requests/s Poisson rate; inf = send all at "
                        "once")
    parser.add_argument("--seed", type=int, default=0)
    main(parser.parse_args())
