"""Online serving benchmark: Poisson arrivals against a live server.

Role parity: reference `benchmarks/benchmark_serving.py` (async request
generator with exponential inter-arrival gaps, per-request latency + TTFT
percentiles, request/token throughput). Start the server first, e.g.:

    python -m intellillm_tpu.entrypoints.openai.api_server --model ... &
    python benchmarks/benchmark_serving.py --backend openai \
        --model <model> --num-prompts 100 --request-rate 4

    python -m intellillm_tpu.entrypoints.api_server --model ... &
    python benchmarks/benchmark_serving.py --backend generate ...

(or use `benchmarks/serve_bench.py`, which boots the server and sweeps
request rates in one command).
"""
from __future__ import annotations

import argparse
import asyncio
import os
import random
import sys
import time
from dataclasses import dataclass
from typing import List, Optional

import aiohttp
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from benchmarks.common import percentiles, sample_requests  # noqa: E402


@dataclass
class RequestResult:
    prompt_len: int
    output_len: int
    latency: float   # e2e seconds
    ttft: float      # time to first streamed chunk, seconds
    n_chunks: int    # streamed SSE chunks received


async def _paced_requests(requests, request_rate: float, rng=None):
    """Poisson pacing. `rng` (np.random.RandomState) makes the arrival
    stream reproducible — two runs with the same seed issue requests on
    the same schedule; None falls back to the unseeded global RNG."""
    sample = (rng.exponential if rng is not None
              else np.random.exponential)
    for req in requests:
        yield req
        if request_rate == float("inf"):
            continue
        await asyncio.sleep(sample(1.0 / request_rate))


async def _replayed_requests(requests, gaps):
    """Recorded pacing: sleep `gaps[i]` seconds before issuing request
    i (gaps come from a captured IWL1 stream's arrival offsets, already
    divided by the replay --speed). Deterministic by construction — no
    RNG anywhere in the schedule."""
    for req, gap in zip(requests, gaps):
        if gap > 0:
            await asyncio.sleep(gap)
        yield req


async def send_request(session: aiohttp.ClientSession, backend: str,
                       api_url: str, model: str, prompt: str,
                       prompt_len: int, output_len: int, best_of: int,
                       results: List[RequestResult]) -> None:
    payload = {
        "prompt": prompt,
        "max_tokens": output_len,
        # best_of > 1 requires sampling (greedy rejects best_of > 1);
        # single-candidate runs measure the deterministic greedy path.
        "temperature": 1.0 if best_of > 1 else 0.0,
        "best_of": best_of,
        "ignore_eos": True,
        "stream": True,
    }
    if backend == "openai":
        payload["model"] = model
    start = time.perf_counter()
    ttft = None
    n_chunks = 0
    async with session.post(api_url, json=payload) as resp:
        resp.raise_for_status()
        async for line in resp.content:
            if not line.strip():
                continue
            if ttft is None:
                ttft = time.perf_counter() - start
            n_chunks += 1
    latency = time.perf_counter() - start
    results.append(RequestResult(prompt_len, output_len, latency,
                                 ttft if ttft is not None else latency,
                                 n_chunks))


async def run_benchmark(backend: str, api_url: str, model: str, requests,
                        request_rate: float, best_of: int = 1,
                        seed: int = None, gaps=None):
    """Drive one pass over `requests`; returns (elapsed_s, results).

    `seed` makes the Poisson arrival schedule reproducible (serve_bench
    threads --seed through here and records it in every summary).
    `gaps` switches to recorded pacing: per-request pre-issue sleeps
    from a captured workload (serve_bench --scenario replay)."""
    results: List[RequestResult] = []
    conn = aiohttp.TCPConnector(limit=0)
    timeout = aiohttp.ClientTimeout(total=6 * 3600)
    if gaps is not None:
        paced = _replayed_requests(requests, gaps)
    else:
        rng = np.random.RandomState(seed) if seed is not None else None
        paced = _paced_requests(requests, request_rate, rng=rng)
    start = time.perf_counter()
    async with aiohttp.ClientSession(connector=conn,
                                     timeout=timeout) as session:
        tasks = []
        async for prompt, prompt_len, output_len in paced:
            tasks.append(asyncio.create_task(
                send_request(session, backend, api_url, model, prompt,
                             prompt_len, output_len, best_of, results)))
        await asyncio.gather(*tasks)
    return time.perf_counter() - start, results


def compute_metrics(results: List[RequestResult], elapsed: float) -> dict:
    lat = [r.latency for r in results]
    ttft = [r.ttft for r in results]
    # True inter-token pace: decode time spread over the tokens streamed
    # after the first chunk. With fused multi-step decode tokens arrive in
    # chunks of up to K, so this is the *average* pace a client observes,
    # not a per-chunk gap.
    tpot = [(r.latency - r.ttft) / max(r.output_len - 1, 1)
            for r in results]
    total_output = sum(r.output_len for r in results)
    return {
        "completed": len(results),
        "elapsed_s": round(elapsed, 2),
        "request_throughput_rps": round(len(results) / elapsed, 3),
        "output_tok_s": round(total_output / elapsed, 1),
        "latency_mean_s": round(float(np.mean(lat)), 3) if lat else None,
        "latency_percentiles_s": {k: round(v, 3)
                                  for k, v in percentiles(lat).items()},
        "ttft_mean_ms": round(float(np.mean(ttft)) * 1e3, 1) if ttft
        else None,
        "ttft_percentiles_ms": {k: round(v * 1e3, 1)
                                for k, v in percentiles(ttft).items()},
        "tpot_mean_ms": round(float(np.mean(tpot)) * 1e3, 2) if tpot
        else None,
        "tpot_percentiles_ms": {k: round(v * 1e3, 2)
                                for k, v in percentiles(tpot).items()},
    }


def build_requests(args, tokenizer):
    raw = sample_requests(args.dataset, args.num_prompts, tokenizer,
                          args.input_len, args.output_len, len(tokenizer),
                          args.seed)
    requests = []
    for prompt_ids, output_len in raw:
        prompt = tokenizer.decode(prompt_ids, skip_special_tokens=True)
        # Re-encode: decode() can merge/split around special tokens, and
        # the server budgets by *its* token count.
        requests.append((prompt, len(tokenizer.encode(prompt)), output_len))
    return requests


def main(args):
    random.seed(args.seed)
    np.random.seed(args.seed)

    from transformers import AutoTokenizer
    tokenizer = AutoTokenizer.from_pretrained(args.tokenizer or args.model)
    requests = build_requests(args, tokenizer)

    api_url = (f"http://{args.host}:{args.port}/v1/completions"
               if args.backend == "openai" else
               f"http://{args.host}:{args.port}/generate")
    elapsed, results = asyncio.run(run_benchmark(
        args.backend, api_url, args.model, requests, args.request_rate,
        args.best_of, seed=args.seed))
    m = compute_metrics(results, elapsed)

    print(f"Completed {m['completed']}/{len(requests)} requests "
          f"in {m['elapsed_s']:.2f} s")
    print(f"Request throughput: {m['request_throughput_rps']:.2f} "
          "requests/s")
    print(f"Output token throughput: {m['output_tok_s']:.1f} tok/s")
    if m["completed"]:
        print(f"Mean latency: {m['latency_mean_s']:.3f} s  "
              + "  ".join(f"{k}={v:.3f}s"
                          for k, v in m["latency_percentiles_s"].items()))
        print(f"Mean TTFT: {m['ttft_mean_ms']:.1f} ms  "
              + "  ".join(f"{k}={v:.1f}ms"
                          for k, v in m["ttft_percentiles_ms"].items()))
        print(f"Mean TPOT: {m['tpot_mean_ms']:.2f} ms/tok  "
              + "  ".join(f"{k}={v:.2f}ms"
                          for k, v in m["tpot_percentiles_ms"].items()))
    return m


def make_arg_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        description="Benchmark online serving throughput/latency.")
    parser.add_argument("--backend", type=str, default="openai",
                        choices=["openai", "generate"])
    parser.add_argument("--host", type=str, default="localhost")
    parser.add_argument("--port", type=int, default=8000)
    parser.add_argument("--model", type=str, required=True,
                        help="model name for the openai endpoint / "
                        "tokenizer source")
    parser.add_argument("--tokenizer", type=str, default=None)
    parser.add_argument("--dataset", type=str, default=None)
    parser.add_argument("--num-prompts", type=int, default=100)
    parser.add_argument("--input-len", type=int, default=128)
    parser.add_argument("--output-len", type=int, default=128)
    parser.add_argument("--best-of", type=int, default=1)
    parser.add_argument("--request-rate", type=float, default=float("inf"),
                        help="requests/s Poisson rate; inf = send all at "
                        "once")
    parser.add_argument("--seed", type=int, default=0)
    return parser


if __name__ == "__main__":
    main(make_arg_parser().parse_args())
