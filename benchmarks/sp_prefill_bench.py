"""Long-prompt prefill timing: flash path on the real chip, and the
ring/Ulysses SP dispatch on a mesh.

VERDICT r4 weak #2 asked for the SP serving path's first perf number.
Constraint: this environment exposes ONE real TPU chip, and sequence
parallelism only exists across chips — so the honest measurement is
(a) single-chip flash prefill wall time vs prompt length on the real
chip (the baseline SP must beat at scale), and (b) ring/Ulysses vs
flash on the 8-virtual-device CPU mesh for RELATIVE sanity (CPU time is
not TPU time; the multi-chip perf claim remains an extrapolation and is
labeled as such wherever quoted).

Prints one JSON line per point. Timings are fetch-synced (np.asarray on
the output), never block_until_ready — the tunnel does not honor it.
"""
from __future__ import annotations

import argparse
import json
import time

import numpy as np


def time_prefill(seq_len: int, size: str, sp_mode: str | None,
                 n_devices: int, iters: int = 3) -> float:
    import jax
    import jax.numpy as jnp

    from intellillm_tpu.ops.pallas.flash_attention import flash_attention
    b, h, d = 1, 32 if size == "7b" else 8, 128
    if sp_mode is None:
        q = jnp.zeros((b, seq_len, h, d), jnp.bfloat16)
        k = v = q
        ctx = jnp.full((b, ), seq_len, jnp.int32)
        scale = d ** -0.5

        if jax.default_backend() == "cpu":
            # Pallas TPU kernels only run under interpret mode on CPU.
            from jax.experimental.pallas import tpu as pltpu

            def run():
                with pltpu.force_tpu_interpret_mode():
                    return flash_attention(q, k, v, ctx, scale)
        else:
            def run():
                return flash_attention(q, k, v, ctx, scale)
    else:
        from jax.sharding import Mesh
        from intellillm_tpu.ops.ring_attention import ring_attention
        from intellillm_tpu.ops.ulysses_attention import ulysses_attention
        devs = np.array(jax.devices()[:n_devices])
        mesh = Mesh(devs.reshape(n_devices, 1), ("data", "model"))
        q = jnp.zeros((b, seq_len, h, d), jnp.bfloat16)
        k = v = q
        fn = ring_attention if sp_mode == "ring" else ulysses_attention

        def run():
            return fn(q, k, v, mesh=mesh, axis="data", causal=True)

    out = run()                     # compile
    np.asarray(out)
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = run()
        np.asarray(out)             # fetch-sync
        times.append(time.perf_counter() - t0)
    return min(times)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", default="7b")
    ap.add_argument("--lengths", default="2048,4096,8192")
    ap.add_argument("--modes", default="flash")
    ap.add_argument("--n-devices", type=int, default=8)
    args = ap.parse_args()
    import jax
    backend = jax.default_backend()
    for mode in args.modes.split(","):
        for sl in (int(x) for x in args.lengths.split(",")):
            sp = None if mode == "flash" else mode
            t = time_prefill(sl, args.size, sp, args.n_devices)
            print(json.dumps({
                "metric": f"prefill-attn {mode} seq={sl} ({backend})",
                "value": round(t * 1e3, 2), "unit": "ms",
                "note": ("single-chip baseline" if sp is None else
                         f"{args.n_devices}-way mesh ({backend})"),
            }), flush=True)


if __name__ == "__main__":
    main()
