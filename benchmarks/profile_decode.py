"""Decode-step component profiler (run on real TPU).

Times each piece of the decode step separately to localize the gap vs the
HBM roofline: weight-streaming matmul floor, paged-attention kernel,
sampler, full K=1 step, fused K-step scan, and host batch prep.

Usage: python benchmarks/profile_decode.py [--size 7b] [--bs 16]
"""
from __future__ import annotations

import argparse
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _sync(out):
    """Force completion with a real device→host fetch: over the axon
    tunnel `block_until_ready` returns before execution finishes, which
    silently turns timings into enqueue-rate measurements."""
    leaf = jax.tree_util.tree_leaves(out)[0]
    np.asarray(jnp.ravel(leaf)[0])


def timeit(fn, *args, n=10, warmup=2, **kw):
    for _ in range(warmup):
        _sync(fn(*args, **kw))
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args, **kw)
    _sync(out)
    return (time.perf_counter() - t0) / n


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", default="7b")
    ap.add_argument("--bs", type=int, default=16)
    ap.add_argument("--ctx", type=int, default=256)
    ap.add_argument("--k", type=int, default=16)
    ap.add_argument("--trace", default=None,
                    help="dir for jax.profiler trace of one fused step")
    ap.add_argument("--kv", default="auto",
                    help="cache dtype (e.g. fp8_e5m2; default bf16)")
    ap.add_argument("--blocks", type=int, default=None,
                    help="KV pool size in blocks (default: size-class)")
    args = ap.parse_args()

    import bench
    default_blocks = {"7b": 512, "1b": 2048, "tiny": 4096}[args.size]
    engine = bench.build_engine(args.size, args.bs, 512,
                                args.blocks if args.blocks is not None
                                else default_blocks,
                                quantization="int8" if args.size == "7b"
                                else None,
                                cache_dtype=args.kv)
    runner = engine.worker.model_runner
    caches = engine.worker.cache_engine.device_cache
    model_config = engine.model_config
    hidden = model_config.get_hidden_size()
    vocab = model_config.get_vocab_size()
    nl = model_config.get_num_layers()
    hq = model_config.hf_config.num_attention_heads
    hkv = model_config.get_total_num_kv_heads()
    d = model_config.get_head_size()
    bs_blk = engine.cache_config.block_size
    b = args.bs
    w = max(32, (args.ctx + bs_blk - 1) // bs_blk)

    params = runner.params
    rng = np.random.default_rng(0)

    # --- A. weight-streaming floor: all decode matmuls, no attention ----
    from intellillm_tpu.layers.quantization import qmatmul

    def matmul_chain(params, x):
        for layer in params["layers"]:
            x = x + qmatmul(qmatmul(x, layer["attn"]["qkv"]),
                            layer["attn"]["o"])[..., :hidden] * 0.0 + x * 1e-9
        return x

    # Inspect actual param tree names first
    names = list(params.keys())
    print("param tree top-level keys:", names)
    lay0 = jax.tree.map(lambda x: (x.shape, str(x.dtype)),
                        params["layers"][0] if "layers" in params else None,
                        is_leaf=lambda x: hasattr(x, "shape"))
    print("layer0:", lay0)

    # --- B. paged attention kernel alone --------------------------------
    from intellillm_tpu.ops.pallas.paged_attention import paged_attention
    nb = caches[0][0].shape[0]
    q = jnp.asarray(rng.normal(size=(b, 1, hq, d)), jnp.bfloat16)
    tables = jnp.asarray(
        rng.integers(0, nb, (b, w)).astype(np.int32))
    ctx = jnp.full((b,), args.ctx, jnp.int32)
    k_cache, v_cache = caches[0]
    t = timeit(lambda: paged_attention(q, k_cache, v_cache, tables, ctx,
                                       d**-0.5), n=20)
    print(f"paged_attention 1 layer [{b=} {hq=} ctx={args.ctx}]: "
          f"{t*1e6:.0f} us  (x{nl} layers = {t*nl*1e3:.1f} ms)")

    # --- C. sampler alone ------------------------------------------------
    from intellillm_tpu.layers.sampler import sample

    hrow = jnp.asarray(rng.normal(size=(b, hidden)), jnp.bfloat16)

    @jax.jit
    def logits_and_sample(params, hrow, seeds):
        logits = runner.model.compute_logits(params, hrow).astype(jnp.float32)
        return sample(logits, jnp.ones((b,), jnp.float32) * 0.0,
                      jnp.full((b,), -1, jnp.int32),
                      jnp.ones((b,), jnp.float32),
                      jnp.zeros((b,), jnp.float32), seeds,
                      logprob_k=8, num_samples=1,
                      do_topk=False, do_topp=False, do_minp=False)

    seeds = jnp.zeros((b,), jnp.uint32)
    t = timeit(logits_and_sample, params, hrow, seeds, n=20)
    print(f"logits+sample [{b=} vocab={vocab}]: {t*1e3:.2f} ms")

    # --- D. full K=1 decode step (device only) ---------------------------
    token_ids = jnp.asarray(rng.integers(0, vocab, (b, 1)), jnp.int32)
    positions = jnp.full((b, 1), args.ctx - 1, jnp.int32)
    zeros = jnp.zeros((b,), jnp.float32)
    ones = jnp.ones((b,), jnp.float32)
    common = dict(logprob_k=8, do_topk=False, do_topp=False, do_minp=False,
                  do_penalties=False)
    dargs = (params, caches, token_ids, positions, tables, ctx,
             zeros, jnp.full((b,), -1, jnp.int32), ones, zeros, seeds,
             zeros, zeros, ones, None, None)

    packed, caches = runner._jit_decode_single(*dargs, **common)
    jax.block_until_ready(packed)
    # re-make args with fresh caches each call (donation!)
    def run_single():
        nonlocal caches
        p, caches = runner._jit_decode_single(
            params, caches, token_ids, positions, tables, ctx,
            zeros, jnp.full((b,), -1, jnp.int32), ones, zeros, seeds,
            zeros, zeros, ones, None, None, **common)
        return p
    t1 = timeit(run_single, n=10)
    print(f"K=1 decode step: {t1*1e3:.1f} ms -> {b/t1:.0f} tok/s")

    # --- E. fused K-step decode ------------------------------------------
    def run_fused():
        nonlocal caches
        p, caches = runner._jit_decode(
            params, caches, token_ids, positions, tables, ctx,
            zeros, jnp.full((b,), -1, jnp.int32), ones, zeros, seeds,
            zeros, zeros, ones, None, None, num_steps=args.k, **common)
        return p
    tk = timeit(run_fused, n=5)
    print(f"K={args.k} fused decode: {tk*1e3:.1f} ms "
          f"({tk/args.k*1e3:.1f} ms/substep) -> {b*args.k/tk:.0f} tok/s")

    if args.trace:
        with jax.profiler.trace(args.trace):
            p = run_fused()
            jax.block_until_ready(p)
        print("trace written to", args.trace)


if __name__ == "__main__":
    main()
