#!/bin/bash
# Round-5 TPU measurement battery — run when the axon tunnel is healthy.
# One TPU process at a time: every step below is sequential, and the
# background availability prober must be paused first.
#
#   touch /tmp/tpu_probe_pause && bash benchmarks/round5_tpu_runbook.sh
#
# Results accumulate in benchmarks/round5_results/.
set -u
cd "$(dirname "$0")/.."
OUT=benchmarks/round5_results
mkdir -p "$OUT"
touch /tmp/tpu_probe_pause                 # one TPU process at a time
trap 'rm -f /tmp/tpu_probe_pause' EXIT

log() { echo "== $(date +%H:%M:%S) $*" | tee -a "$OUT/runbook.log"; }

run() { # name, env..., -- cmd...
  local name=$1; shift
  log "start $name"
  "$@" >"$OUT/$name.json" 2>"$OUT/$name.err"
  log "done $name rc=$? -> $(tail -c 300 "$OUT/$name.json" | tr '\n' ' ')"
}

# 1. Headline (hardened bench; also first pipelined offline number).
run headline_pipelined python bench.py
run headline_nopipeline env INTELLILLM_PIPELINE=0 python bench.py
# With pipelining the fetch no longer needs K-huge amortization (out==K
# means ONE fused call and no continuations at the default shape) —
# smaller K with chained continuations may now win:
run headline_k64 env INTELLILLM_BENCH_K=64 python bench.py
run headline_k32 env INTELLILLM_BENCH_K=32 python bench.py

# 2. bs sweep incl. the BASELINE-named bs=256 config.
for bs in 64 96 128 192 256; do
  run "bs_sweep_$bs" env INTELLILLM_BENCH_BS=$bs python bench.py
done

# 3. Long context: retune pool at mml=2048 and add mml=4096.
run longctx_2048 env INTELLILLM_BENCH_MML=2048 INTELLILLM_BENCH_IN=1024 \
    INTELLILLM_BENCH_BS=16 python bench.py
run longctx_2048_big_pool env INTELLILLM_BENCH_MML=2048 \
    INTELLILLM_BENCH_IN=1024 INTELLILLM_BENCH_BS=24 \
    INTELLILLM_BENCH_BLOCKS=1800 python bench.py
run longctx_2048_block32 env INTELLILLM_BENCH_MML=2048 \
    INTELLILLM_BENCH_IN=1024 INTELLILLM_BENCH_BS=16 \
    INTELLILLM_BENCH_BLOCK_SIZE=32 python bench.py
run longctx_4096 env INTELLILLM_BENCH_MML=4096 INTELLILLM_BENCH_IN=3072 \
    INTELLILLM_BENCH_BS=8 INTELLILLM_BENCH_BLOCKS=1800 python bench.py

# 3b. Prefill attention wall time vs length (flash, real chip).
run sp_prefill python benchmarks/sp_prefill_bench.py --size 7b \
    --lengths 2048,4096,8192 --modes flash

# 4. Serving sweep (north star): pipelined vs not.
run serve_pipelined python benchmarks/serve_bench.py --size 7b \
    --quantization int8 --kv-cache-dtype fp8_e5m2 \
    --num-device-blocks 1600 --max-num-seqs 96 --rates 2,4,8,12,16,inf
run serve_nopipeline env INTELLILLM_PIPELINE=0 \
    python benchmarks/serve_bench.py --size 7b --quantization int8 \
    --kv-cache-dtype fp8_e5m2 --num-device-blocks 1600 \
    --max-num-seqs 96 --rates 8,16

# 4b. Disaggregated prefill/decode A/B: 1 prefill + 2 decode replicas
# vs 3 mixed, probe TTFT vs background P99 TPOT, plus what the
# isolation costs in KV-transfer bytes/seconds (docs/routing.md).
run serve_disagg python benchmarks/serve_bench.py --size 7b \
    --scenario disagg --num-replicas 2 --quantization int8 \
    --kv-cache-dtype fp8_e5m2 --num-device-blocks 1600 \
    --max-num-seqs 96

# 5. Real-checkpoint load validation (task 8).
run real_checkpoint python benchmarks/real_checkpoint_tpu.py

# 6. Speculative machinery bracketing.
run spec_bracket python benchmarks/spec_bench.py --k 4 --bs 32 --out 64

log "runbook complete"
