"""Paged-attention decode kernel microbenchmark.

Role parity: reference `benchmarks/kernels/benchmark_paged_attention.py`
(per-call μs over a shape grid). Compares the Pallas kernel against the
jnp block-table-gather reference on the same inputs.

Usage:
    python benchmarks/kernels/benchmark_paged_attention.py \
        --batch-size 32 --context-len 1024 --num-query-heads 32 \
        --num-kv-heads 32 --head-size 128
"""
from __future__ import annotations

import argparse
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from intellillm_tpu.ops.attention import decode_attention_reference
from intellillm_tpu.ops.pallas.paged_attention import paged_attention
from intellillm_tpu.utils import cdiv


def build_inputs(args, seed=0):
    rng = np.random.default_rng(seed)
    bs = args.block_size
    max_blocks = cdiv(args.context_len, bs)
    num_blocks = max(args.batch_size * max_blocks + 1, 128)

    dt = jnp.dtype(args.dtype)
    q = jnp.asarray(rng.standard_normal(
        (args.batch_size, 1, args.num_query_heads, args.head_size)), dt)
    k_cache = jnp.asarray(rng.standard_normal(
        (num_blocks, args.num_kv_heads, bs, args.head_size)), dt)
    v_cache = jnp.asarray(rng.standard_normal(
        (num_blocks, args.num_kv_heads, bs, args.head_size)), dt)
    tables = jnp.asarray(
        rng.permutation(args.batch_size * max_blocks).reshape(
            args.batch_size, max_blocks).astype(np.int32))
    ctx = jnp.full((args.batch_size, ), args.context_len, jnp.int32)
    slopes = None
    if args.use_alibi:
        slopes = jnp.asarray(
            rng.standard_normal(args.num_query_heads).astype(np.float32))
    return q, k_cache, v_cache, tables, ctx, slopes


def timeit(fn, *args, n=50, warmup=5):
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    start = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - start) / n


def main(args):
    q, k_cache, v_cache, tables, ctx, slopes = build_inputs(args)
    scale = args.head_size**-0.5

    pallas_fn = jax.jit(lambda *a: paged_attention(*a, scale, slopes))
    ref_fn = jax.jit(
        lambda *a: decode_attention_reference(*a, scale, slopes))

    # Numerics check first.
    out_p = np.asarray(pallas_fn(q, k_cache, v_cache, tables, ctx),
                       np.float32)
    out_r = np.asarray(ref_fn(q, k_cache, v_cache, tables, ctx), np.float32)
    err = np.abs(out_p - out_r).max()
    print(f"max |pallas - reference| = {err:.3e}")

    t_pallas = timeit(pallas_fn, q, k_cache, v_cache, tables, ctx)
    t_ref = timeit(ref_fn, q, k_cache, v_cache, tables, ctx)

    kv_bytes = (2 * args.batch_size * cdiv(args.context_len, args.block_size)
                * args.block_size * args.num_kv_heads * args.head_size
                * jnp.dtype(args.dtype).itemsize)
    print(f"pallas   : {t_pallas * 1e6:9.1f} us  "
          f"({kv_bytes / t_pallas / 1e9:6.1f} GB/s KV read)")
    print(f"reference: {t_ref * 1e6:9.1f} us  "
          f"({kv_bytes / t_ref / 1e9:6.1f} GB/s KV read)")
    print(f"speedup  : {t_ref / t_pallas:.2f}x")


if __name__ == "__main__":
    parser = argparse.ArgumentParser(
        description="Benchmark the paged-attention decode kernel.")
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--context-len", type=int, default=1024)
    parser.add_argument("--num-query-heads", type=int, default=32)
    parser.add_argument("--num-kv-heads", type=int, default=32)
    parser.add_argument("--head-size", type=int, default=128)
    parser.add_argument("--block-size", type=int, default=16)
    parser.add_argument("--dtype", type=str, default="bfloat16")
    parser.add_argument("--use-alibi", action="store_true")
    main(parser.parse_args())
