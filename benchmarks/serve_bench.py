"""One-command serving benchmark: boot the OpenAI server on a dummy
checkpoint, warm it up, then sweep request rates with
`benchmark_serving.py`'s Poisson load generator.

This is the north-star harness (BASELINE.json: Llama-2-7B via
entrypoints/openai, aggregate output tok/s + p50 TTFT measured at the
HTTP boundary — reference `.buildkite/run-benchmarks.sh:25-30`). Example:

    python benchmarks/serve_bench.py --size 7b --quantization int8 \
        --kv-cache-dtype fp8_e5m2 --num-device-blocks 1600 \
        --max-num-seqs 96 --rates 2,4,8,inf

Prints one JSON line per rate plus a `serve_bench_summary` line.
"""
from __future__ import annotations

import argparse
import asyncio
import hashlib
import json
import math
import os
import random
import signal
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from benchmarks.benchmark_serving import (build_requests,  # noqa: E402
                                          compute_metrics, run_benchmark)
from benchmarks.common import save_dummy_checkpoint  # noqa: E402


def launch_server(model_dir: str, args,
                  scheduling_policy: str = None) -> subprocess.Popen:
    cmd = [
        sys.executable, "-m",
        "intellillm_tpu.entrypoints.openai.api_server",
        "--model", model_dir,
        "--load-format", "dummy",
        "--served-model-name", f"dummy-{args.size}",
        "--port", str(args.port),
        "--max-model-len", str(args.max_model_len),
        "--max-num-seqs", str(args.max_num_seqs),
        "--num-decode-steps", str(args.num_decode_steps),
        "--block-size", str(args.block_size),
        "--kv-cache-dtype", args.kv_cache_dtype,
        "--max-paddings", "4096",
        "--swap-space", "0.05",
        "--disable-log-requests",
    ]
    if args.quantization:
        cmd += ["--quantization", args.quantization]
    if args.num_device_blocks:
        cmd += ["--num-device-blocks-override", str(args.num_device_blocks)]
    if args.disable_chunked_prefill:
        cmd += ["--disable-chunked-prefill"]
    if args.max_num_batched_tokens:
        cmd += ["--max-num-batched-tokens",
                str(args.max_num_batched_tokens)]
    if scheduling_policy:
        cmd += ["--scheduling-policy", scheduling_policy]
    if getattr(args, "sjf_starvation_s", None) is not None:
        cmd += ["--sjf-starvation-s", str(args.sjf_starvation_s)]
    if getattr(args, "predictor_path", None):
        cmd += ["--predictor-path", args.predictor_path]
    if getattr(args, "_spec_model_dir", None):
        cmd += ["--speculative-model", args._spec_model_dir,
                "--num-speculative-tokens",
                str(args.num_speculative_tokens)]
        if args.spec_k_min is not None:
            cmd += ["--spec-k-min", str(args.spec_k_min)]
        if args.spec_k_max is not None:
            cmd += ["--spec-k-max", str(args.spec_k_max)]
    env = dict(os.environ)
    env.setdefault("HF_HUB_OFFLINE", "1")
    # Server logs go to a file, not an undrained pipe (a full pipe buffer
    # would block the server's logging mid-benchmark).
    log = open(args.server_log, "wb")
    return subprocess.Popen(cmd, env=env, stdout=log,
                            stderr=subprocess.STDOUT)


def snapshot_observability(base: str) -> dict:
    """Scrape /metrics and distill the step-phase histograms and XLA
    compile counters into a compact dict for the summary JSON, so BENCH
    files carry latency attribution next to throughput."""
    try:
        with urllib.request.urlopen(base + "/metrics", timeout=5) as r:
            text = r.read().decode(errors="replace")
    except Exception as e:
        return {"error": f"metrics scrape failed: {e}"}

    phase_sum: dict = {}
    phase_count: dict = {}
    out = {"step_phase_seconds": phase_sum, "step_phase_samples": phase_count,
           "xla_compiles": {}, "xla_cache_hits": {},
           "xla_compile_time_seconds": {}, "kernel_dispatch": {}}
    simple = {"intellillm_xla_compiles_total": ("xla_compiles", "program"),
              "intellillm_xla_cache_hits_total":
                  ("xla_cache_hits", "program"),
              "intellillm_kernel_dispatch_total":
                  ("kernel_dispatch", "path")}
    for line in text.splitlines():
        if line.startswith("#") or not line.strip():
            continue
        try:
            name_labels, value = line.rsplit(None, 1)
            value = float(value)
            name, _, labels = name_labels.partition("{")
            labels = dict(
                kv.split("=", 1) for kv in labels.rstrip("}").split(",")
                if "=" in kv) if labels else {}
            labels = {k: v.strip('"') for k, v in labels.items()}
        except ValueError:
            continue
        if name == "intellillm_step_phase_seconds_sum":
            phase_sum[labels.get("phase", "?")] = value
        elif name == "intellillm_step_phase_seconds_count":
            phase_count[labels.get("phase", "?")] = value
        elif name == "intellillm_step_time_seconds_sum":
            out["step_time_seconds_sum"] = value
        elif name == "intellillm_step_time_seconds_count":
            out["step_count"] = value
        elif name == "intellillm_xla_compile_time_seconds_sum":
            out["xla_compile_time_seconds"][
                labels.get("program", "?")] = value
        elif name == "intellillm_live_executables":
            out["live_executables"] = value
        elif name in simple:
            key, label = simple[name]
            out[key][labels.get(label, "?")] = value
    return out


def snapshot_router_metrics(base: str) -> dict:
    """Distill the router's `intellillm_router_*` families into a compact
    dict: per-replica request counts / predicted load / health, decision
    and failover counters."""
    try:
        with urllib.request.urlopen(base + "/metrics", timeout=5) as r:
            text = r.read().decode(errors="replace")
    except Exception as e:
        return {"error": f"router metrics scrape failed: {e}"}

    out = {"requests_total": {}, "decisions": {}, "failovers": {},
           "predicted_load_tokens": {}, "replica_healthy": {},
           "queue_depth": {}}
    families = {
        "intellillm_router_requests_total": ("requests_total", "replica"),
        "intellillm_router_routing_decisions_total":
            ("decisions", "decision"),
        "intellillm_router_failovers_total": ("failovers", "replica"),
        "intellillm_router_predicted_load_tokens":
            ("predicted_load_tokens", "replica"),
        "intellillm_router_replica_healthy": ("replica_healthy", "replica"),
    }
    for line in text.splitlines():
        if line.startswith("#") or not line.strip():
            continue
        try:
            name_labels, value = line.rsplit(None, 1)
            value = float(value)
            name, _, labels = name_labels.partition("{")
            labels = dict(
                kv.split("=", 1) for kv in labels.rstrip("}").split(",")
                if "=" in kv) if labels else {}
            labels = {k: v.strip('"') for k, v in labels.items()}
        except ValueError:
            continue
        if name in families:
            key, label = families[name]
            out[key][labels.get(label, "?")] = value
        elif name == "intellillm_router_replica_queue_depth":
            out["queue_depth"].setdefault(
                labels.get("replica", "?"), {})[
                    labels.get("queue", "?")] = value
    return out


def snapshot_health_detail(base: str) -> dict:
    """Scrape the server-side /health/detail body (rolling SLO summary,
    device telemetry, watchdog state). A 503 still carries the body
    (stalled server — worth recording)."""
    try:
        with urllib.request.urlopen(base + "/health/detail", timeout=5) as r:
            return json.loads(r.read().decode(errors="replace"))
    except urllib.error.HTTPError as e:
        try:
            return json.loads(e.read().decode(errors="replace"))
        except Exception:
            return {"error": f"health/detail scrape failed: {e}"}
    except Exception as e:
        return {"error": f"health/detail scrape failed: {e}"}


def snapshot_efficiency(base: str) -> dict:
    """Scrape the full compute-efficiency ledger (/debug/efficiency):
    real/pad token totals, per-axis fill ratios, MFU, and the per-bucket
    pad-waste attribution — the numbers every bucketing/scheduler perf
    change is judged against."""
    try:
        with urllib.request.urlopen(base + "/debug/efficiency",
                                    timeout=5) as r:
            return json.loads(r.read().decode(errors="replace"))
    except Exception as e:
        return {"error": f"efficiency scrape failed: {e}"}


def snapshot_kernels(base: str, top: int = 8) -> dict:
    """Scrape the per-kernel cost ledger (/debug/kernels): per-(program,
    bucket) cost_analysis FLOPs / bytes / peak HBM, the cost-model-vs-
    analytic MFU cross-check, and any merged profiler capture — the
    before/after artifact a Pallas kernel pass is judged against
    (ROADMAP item 2)."""
    try:
        with urllib.request.urlopen(f"{base}/debug/kernels?top={top}",
                                    timeout=5) as r:
            return json.loads(r.read().decode(errors="replace"))
    except Exception as e:
        return {"error": f"kernels scrape failed: {e}"}


def snapshot_alerts(base: str) -> dict:
    """Scrape /debug/alerts. On a router this includes the fleet block
    (every replica's alert summary aggregated), so a fleet run can
    assert "no alerts fired" from one endpoint."""
    try:
        with urllib.request.urlopen(base + "/debug/alerts", timeout=5) as r:
            return json.loads(r.read().decode(errors="replace"))
    except Exception as e:
        return {"error": f"alerts scrape failed: {e}"}


def distill_alerts(alerts: dict) -> dict:
    """Compact alert verdict for the summary line: which rules are
    firing/pending and whether the run finished clean."""
    if not alerts or "error" in alerts:
        return {"error": (alerts or {}).get("error", "no alert data"),
                "clean": None}
    fleet = alerts.get("fleet")
    firing = sorted((fleet.get("rules_firing") or []) if fleet
                    else (alerts.get("firing") or []))
    pending = sorted((fleet.get("rules_pending") or []) if fleet
                     else (alerts.get("pending") or []))
    return {
        "firing": firing,
        "pending": pending,
        "page_firing": (fleet.get("page_firing") if fleet
                        else alerts.get("page_firing", False)),
        "clean": not firing and not pending,
        "fleet_aggregated": fleet is not None,
    }


def distill_contention(detail: dict) -> dict:
    """Compact the scheduler's contention ledger (the `contention` block
    of /health/detail, backed by intellillm_sched_deferred_seconds_total
    / intellillm_sched_decisions_total) into the A/B-comparable block:
    deferred-seconds-by-cause plus the preemption/requeue counts — the
    *why* next to every scenario's queue-wait numbers."""
    block = (detail or {}).get("contention")
    if not block:
        return {"error": (detail or {}).get(
            "error", "no contention block in /health/detail")}
    decisions = block.get("decisions") or {}
    return {
        "deferred_seconds_by_cause":
            block.get("deferred_seconds_by_cause") or {},
        "decisions": decisions,
        "preemptions": decisions.get("preempt_victim", 0),
        "requeues": decisions.get("requeue", 0),
    }


def snapshot_contention(base: str) -> dict:
    """distill_contention over a fresh /health/detail scrape."""
    return distill_contention(snapshot_health_detail(base))


def distill_numerics(detail: dict) -> dict:
    """Compact the output-integrity block (the `numerics` block of
    /health/detail, obs/numerics.py) for the summary: sentinel coverage
    + anomaly/quarantine counts and the KV-audit checksum/mismatch
    counters. wdiff diffs these with lower-is-better direction — a run
    is only comparable to a baseline if neither corrupted outputs."""
    block = (detail or {}).get("numerics")
    if not block:
        return {"error": (detail or {}).get(
            "error", "no numerics block in /health/detail")}
    return block


def snapshot_numerics(base: str) -> dict:
    """Scrape /debug/numerics. On a router this is the fleet view: the
    divergence-canary ledger plus each replica's compact block."""
    try:
        with urllib.request.urlopen(base + "/debug/numerics",
                                    timeout=5) as r:
            return json.loads(r.read().decode(errors="replace"))
    except Exception as e:
        return {"error": f"numerics scrape failed: {e}"}


def snapshot_fleet_traces(router_base: str, limit: int = 3) -> dict:
    """Sample stitched fleet traces from the router: recent trace ids
    from /debug/trace, each fetched via /debug/trace/{id} — the per-hop
    attribution (router_queue / routing / network / replica_queue /
    prefill / decode) is the fleet-level answer to "where did the
    latency go". Returns {"samples": [...], "hops_mean_ms": {...}}."""
    out = {"samples": [], "hops_mean_ms": {}}
    try:
        with urllib.request.urlopen(router_base + "/debug/trace",
                                    timeout=5) as r:
            listing = json.loads(r.read().decode(errors="replace"))
    except Exception as e:
        return {"error": f"trace listing scrape failed: {e}"}
    hop_sums, hop_counts = {}, {}
    for trace_id in (listing.get("recent_trace_ids") or [])[:limit]:
        try:
            with urllib.request.urlopen(
                    f"{router_base}/debug/trace/{trace_id}",
                    timeout=5) as r:
                stitched = json.loads(r.read().decode(errors="replace"))
        except Exception:
            continue
        attribution = stitched.get("attribution") or {}
        out["samples"].append({
            "trace_id": trace_id,
            "hops": stitched.get("hops"),
            "e2e_s": attribution.get("e2e_s"),
            "hops_s": attribution.get("hops_s"),
            "num_events": len(stitched.get("timeline") or []),
        })
        for hop, seconds in (attribution.get("hops_s") or {}).items():
            hop_sums[hop] = hop_sums.get(hop, 0.0) + seconds
            hop_counts[hop] = hop_counts.get(hop, 0) + 1
    out["hops_mean_ms"] = {
        hop: round(hop_sums[hop] / hop_counts[hop] * 1e3, 3)
        for hop in hop_sums}
    return out


def distill_device_telemetry(detail: dict) -> dict:
    """Compact memory-state record for the summary JSON: per-device
    peak/in-use bytes, the ledger, headroom, and total swap traffic."""
    dt = detail.get("device_telemetry") or {}
    return {
        "devices": dt.get("devices") or {},
        "ledger_bytes": dt.get("ledger_bytes") or {},
        "headroom_ratio": dt.get("headroom_ratio"),
        "low_hbm_warnings": dt.get("low_hbm_warnings"),
        "swap_bytes_total": dt.get("swap_bytes_total") or {},
    }


def wait_healthy(proc: subprocess.Popen, base: str, timeout: float,
                 server_log: str) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            with open(server_log, "rb") as f:
                out = f.read().decode(errors="replace")
            raise RuntimeError(f"server died during init:\n{out[-4000:]}")
        try:
            urllib.request.urlopen(base + "/health", timeout=2)
            return
        except Exception:
            time.sleep(2.0)
    raise TimeoutError(f"server not healthy after {timeout:.0f}s")


async def _ttft_under_load(api_url: str, model_name: str, background,
                           probe, probe_delay: float,
                           protocol: str = "openai"):
    """Steady decode stream + one long-prompt probe injected mid-run.

    The background requests all start at once (short prompts, long
    outputs) so the engine is in pure decode when the probe's long
    prefill arrives. With legacy homogeneous scheduling that prefill
    monopolizes whole steps — decode TPOT spikes and the probe still
    waits behind the running batch; with chunked prefill the prompt
    rides the per-step slack. Returns (elapsed_s, bg_results,
    probe_results)."""
    import aiohttp

    from benchmarks.benchmark_serving import send_request

    bg_results, probe_results = [], []
    conn = aiohttp.TCPConnector(limit=0)
    timeout = aiohttp.ClientTimeout(total=6 * 3600)
    start = time.perf_counter()
    async with aiohttp.ClientSession(connector=conn,
                                     timeout=timeout) as session:
        bg_tasks = [
            asyncio.create_task(send_request(
                session, protocol, api_url, model_name, prompt,
                prompt_len, output_len, 1, bg_results))
            for prompt, prompt_len, output_len in background
        ]
        await asyncio.sleep(probe_delay)
        prompt, prompt_len, output_len = probe
        await send_request(session, protocol, api_url, model_name, prompt,
                           prompt_len, output_len, 1, probe_results)
        await asyncio.gather(*bg_tasks)
    return time.perf_counter() - start, bg_results, probe_results


def run_ttft_under_load(args, api_url: str, model_name: str, tokenizer,
                        requests, protocol: str = "openai") -> dict:
    """The ttft-under-load scenario: report the probe's TTFT next to the
    background stream's P99 TPOT — the pair of numbers chunked prefill
    trades against each other."""
    import copy

    probe_args = copy.copy(args)
    probe_args.num_prompts = 1
    probe_args.input_len = (args.probe_input_len
                            or max(args.input_len,
                                   args.max_model_len
                                   - args.probe_output_len - 1))
    probe_args.output_len = args.probe_output_len
    probe_args.seed = args.seed + 1
    (probe,) = build_requests(probe_args, tokenizer)

    # Warm the probe-shaped prefill program so the measured TTFT is
    # scheduling delay, not a first-compile stall.
    asyncio.run(run_benchmark(protocol, api_url, model_name, [probe],
                              float("inf")))

    elapsed, bg_results, probe_results = asyncio.run(_ttft_under_load(
        api_url, model_name, requests, probe, args.probe_delay,
        protocol=protocol))
    bg = compute_metrics(bg_results, elapsed)
    (pr,) = probe_results
    return {
        "scenario": "ttft-under-load",
        "seed": args.seed,
        "chunked_prefill": not args.disable_chunked_prefill,
        "max_num_batched_tokens": args.max_num_batched_tokens,
        "probe_input_len": probe[1],
        "probe_output_len": probe[2],
        "probe_delay_s": args.probe_delay,
        "probe_ttft_ms": round(pr.ttft * 1e3, 1),
        "probe_latency_s": round(pr.latency, 3),
        "background_completed": bg["completed"],
        "background_tpot_p99_ms": bg["tpot_percentiles_ms"]["p99"],
        "background_ttft_p99_ms": bg["ttft_percentiles_ms"]["p99"],
        "background": bg,
    }


# ---------------------------------------------------------------------------
# Workload capture & replay (docs/observability.md).
#
# `--scenario replay` re-issues a captured IWL1 stream (obs/workload.py)
# against a freshly booted server with the original inter-arrival gaps;
# `--scenario diurnal` synthesizes a seeded day-in-the-life stream (flash
# crowds, heavy-tailed lengths, adapter churn) in the same format.  Both
# are deterministic end to end: two replays of the same file issue the
# identical request sequence, and the server-side re-capture
# (/debug/workload?format=iwl) matches across repeats.
# ---------------------------------------------------------------------------


def _synth_prompt(tokenizer, prompt_len: int, prompt_hash: str):
    """Deterministically resynthesize a prompt from its fingerprint.

    Captures default to hashes, not raw text (privacy).  Replay only
    needs *a* stable prompt of the recorded token length, so we sample
    token ids from an RNG seeded by the fingerprint: every replay of the
    same record produces the same prompt string.  Returns
    (prompt, server_token_count) like build_requests."""
    rng = random.Random(int(prompt_hash or "0", 16))
    vocab = len(tokenizer)
    ids = [rng.randrange(vocab) for _ in range(max(1, prompt_len))]
    prompt = tokenizer.decode(ids, skip_special_tokens=True)
    if not prompt.strip():
        prompt = " ".join(str(rng.randrange(10)) for _ in range(
            max(1, prompt_len)))
    # Re-encode: the server budgets by *its* token count (see
    # build_requests in benchmark_serving.py).
    return prompt, len(tokenizer.encode(prompt))


def build_replay_stream(records, tokenizer, args):
    """Turn parsed IWL1 records into (requests, gaps, stream_digest).

    `requests` is the (prompt, prompt_len, output_len) list
    run_benchmark expects; `gaps[i]` is the sleep before issuing request
    i (recorded offsets divided by --speed); `stream_digest` is a sha256
    over the exact issue schedule so two replays can be compared without
    trusting wall clocks."""
    speed = max(float(args.speed), 1e-6)
    requests, gaps = [], []
    h = hashlib.sha256()
    prev_t = 0.0
    for rec in records:
        t = float(rec.get("t", 0.0))
        gap = max(0.0, (t - prev_t) / speed)
        prev_t = t
        plen = int(rec.get("prompt_len") or 1)
        if rec.get("prompt"):
            prompt = rec["prompt"]
            plen = len(tokenizer.encode(prompt))
        else:
            prompt, plen = _synth_prompt(tokenizer, plen,
                                         rec.get("prompt_hash") or "0")
        sampling = rec.get("sampling") or {}
        outcome = rec.get("outcome") or {}
        olen = int(sampling.get("max_tokens") or outcome.get("tokens")
                   or args.output_len)
        olen = max(1, min(olen, args.max_model_len - plen - 1))
        requests.append((prompt, plen, olen))
        gaps.append(round(gap, 6))
        h.update(json.dumps(
            [gaps[-1], rec.get("prompt_hash") or "", plen, olen],
            sort_keys=True).encode())
    return requests, gaps, h.hexdigest()[:16]


def _recapture_digest(records) -> str:
    """Order-insensitive digest of a re-captured workload shard.

    Concurrent arrivals can land in the server's log in either order,
    so the digest covers the sorted multiset of per-request tuples, not
    the sequence."""
    tuples = sorted(
        [rec.get("prompt_hash") or "", int(rec.get("prompt_len") or 0),
         (rec.get("sampling") or {}).get("max_tokens"),
         (rec.get("outcome") or {}).get("tokens"),
         (rec.get("outcome") or {}).get("reason")]
        for rec in records)
    return hashlib.sha256(
        json.dumps(tuples, sort_keys=True).encode()).hexdigest()[:16]


def _fetch_iwl(base: str) -> str:
    with urllib.request.urlopen(base + "/debug/workload?format=iwl",
                                timeout=10.0) as r:
        return r.read().decode()


def run_replay(args, model_dir, tokenizer, extra=None) -> dict:
    """Replay a captured IWL1 workload against one freshly booted server.

    Boots once, then runs the stream --replay-repeat times.  Each pass
    records client-side metrics plus a server-side re-capture digest
    from /debug/workload, so the summary can assert end-to-end
    determinism (identical issue schedule AND identical server-observed
    workload) instead of asking the reader to diff logs."""
    from intellillm_tpu.obs.workload import parse_iwl

    if not args.workload:
        raise SystemExit("--scenario replay requires --workload FILE")
    with open(args.workload) as f:
        header, records = parse_iwl(f.read())
    requests, gaps, stream_digest = build_replay_stream(
        records, tokenizer, args)

    proc = launch_server(model_dir, args)
    base = f"http://127.0.0.1:{args.port}"
    api_url = base + "/v1/completions"
    model_name = f"dummy-{args.size}"
    summary = {"scenario": "replay", "size": args.size,
               "seed": args.seed, "workload": args.workload,
               "speed": args.speed, "replay_repeat": args.replay_repeat,
               "num_requests": len(requests),
               "workload_header": {k: header.get(k) for k in
                                   ("iwl", "source", "raw_prompts",
                                    "requests")},
               "stream_digest": stream_digest,
               "max_num_seqs": args.max_num_seqs, "results": []}
    if extra:
        summary.update(extra)
    recaptures = []
    try:
        wait_healthy(proc, base, args.init_timeout, args.server_log)
        # Warm the batch/width ladder the replayed stream will hit (same
        # rationale as run_single's warm-up): two all-at-once passes over
        # a prefix so first-compile stalls don't skew repeat 1 vs 2.
        warm = requests[:max(4, min(args.max_num_seqs, len(requests)))]
        for _ in range(2):
            asyncio.run(run_benchmark("openai", api_url, model_name,
                                      warm, float("inf")))
        for rep in range(max(1, args.replay_repeat)):
            mark = time.time()
            elapsed, results = asyncio.run(run_benchmark(
                "openai", api_url, model_name, requests, float("inf"),
                gaps=gaps))
            m = compute_metrics(results, elapsed)
            m["repeat"] = rep
            recap = {"count": None, "digest": None}
            try:
                _, caught = parse_iwl(_fetch_iwl(base))
                shard = [r for r in caught
                         if float(r.get("ts") or 0.0) >= mark]
                recap = {"count": len(shard),
                         "digest": _recapture_digest(shard)}
                if args.workload_out:
                    from intellillm_tpu.obs.workload import dump_iwl
                    with open(args.workload_out, "w") as f:
                        f.write(dump_iwl(shard, source="replay"))
            except Exception as e:  # recapture is best-effort
                recap["error"] = str(e)
            m["recapture"] = recap
            recaptures.append(recap.get("digest"))
            summary["results"].append(m)
            print(json.dumps({"serve_bench_replay_repeat": rep, **m}),
                  flush=True)
        summary["recapture_digests"] = recaptures
        summary["recapture_match"] = (
            len(set(d for d in recaptures)) == 1
            and recaptures[0] is not None)
        summary["replay_deterministic"] = bool(summary["recapture_match"])
        summary["observability"] = snapshot_observability(base)
        detail = snapshot_health_detail(base)
        summary["slo"] = detail.get("slo") or {}
        summary["efficiency"] = snapshot_efficiency(base)
        summary["kernels"] = snapshot_kernels(base)
        summary["contention"] = distill_contention(detail)
        summary["numerics"] = distill_numerics(detail)
        summary["alerts"] = distill_alerts(snapshot_alerts(base))
    finally:
        proc.send_signal(signal.SIGKILL)
        proc.wait()

    print(json.dumps({"serve_bench_summary": summary}), flush=True)
    return summary


def synth_diurnal(args):
    """Synthesize a seeded diurnal workload as IWL1 records.

    ~60% of arrivals are spread uniformly over --diurnal-duration; the
    rest cluster into --diurnal-bursts gaussian flash crowds.  Prompt
    and output lengths are heavy-tailed (lognormal, clamped to the
    context window); requests churn across --num-tenants adapters with
    a Zipf-ish 1/k weighting so adapter-cache behaviour is exercised.
    Same --seed => byte-identical record list."""
    rng = random.Random(args.seed)
    n = args.num_prompts
    dur = max(0.001, float(args.diurnal_duration))
    bursts = max(0, int(args.diurnal_bursts))
    centers = [rng.uniform(0.15, 0.85) * dur for _ in range(bursts)]
    arrivals = []
    for i in range(n):
        if bursts and rng.random() < 0.4:
            c = centers[rng.randrange(bursts)]
            arrivals.append(min(dur, max(0.0,
                                         rng.gauss(c, dur * 0.02))))
        else:
            arrivals.append(rng.uniform(0.0, dur))
    arrivals.sort()
    tenants = max(1, args.num_tenants)
    weights = [1.0 / k for k in range(1, tenants + 1)]
    records = []
    for i, t in enumerate(arrivals):
        plen = int(min(args.max_model_len // 2, max(
            4, rng.lognormvariate(math.log(args.input_len), 0.6))))
        olen = int(min(args.max_model_len - plen - 1, max(
            1, rng.lognormvariate(math.log(args.output_len), 0.6))))
        adapter = rng.choices(range(tenants), weights=weights)[0]
        phash = hashlib.blake2b(
            f"{args.seed}:{i}".encode(), digest_size=8).hexdigest()
        records.append({
            "ts": round(t, 6), "t": round(t, 6),
            "id": f"diurnal-{args.seed}-{i}",
            "prompt_len": plen, "prompt_hash": phash,
            "sampling": {"max_tokens": olen, "temperature": 0.0,
                         "ignore_eos": True},
            "tenant": f"tenant-{adapter}" if adapter else None,
            "adapter": adapter, "priority": 0,
            "outcome": {"tokens": olen, "reason": "synthetic"},
        })
    return records


def run_diurnal(args, model_dir, tokenizer) -> dict:
    """Emit a synthetic diurnal IWL1 stream, then (unless --emit-only)
    replay it through run_replay."""
    from intellillm_tpu.obs.workload import dump_iwl

    records = synth_diurnal(args)
    out = args.workload_out or "/tmp/serve_bench_diurnal.iwl.jsonl"
    with open(out, "w") as f:
        f.write(dump_iwl(records, source="diurnal",
                         extra_header={"seed": args.seed}))
    block = {"scenario": "diurnal", "seed": args.seed,
             "num_requests": len(records), "workload_out": out,
             "diurnal_duration_s": args.diurnal_duration,
             "diurnal_bursts": args.diurnal_bursts,
             "num_tenants": args.num_tenants}
    print(json.dumps({"serve_bench_diurnal": block}), flush=True)
    if args.emit_only:
        summary = dict(block, emit_only=True)
        print(json.dumps({"serve_bench_summary": summary}), flush=True)
        return summary
    args.workload = out
    args.workload_out = None  # don't clobber the input mid-replay
    return run_replay(args, model_dir, tokenizer,
                      extra={"diurnal": block})


def launch_generate_replica(model_dir: str, args, port: int,
                            log_path: str,
                            role: str = None,
                            extra_args=None) -> subprocess.Popen:
    """Launch one demo api_server replica (plain /generate protocol —
    the surface the router fronts). `role` maps to --replica-role for
    disaggregated fleets; `extra_args` appends raw CLI flags (the
    multi-tenant scenario passes the LoRA/fairness knobs through it)."""
    cmd = [
        sys.executable, "-m", "intellillm_tpu.entrypoints.api_server",
        "--model", model_dir,
        "--load-format", "dummy",
        "--host", "127.0.0.1",
        "--port", str(port),
        "--max-model-len", str(args.max_model_len),
        "--max-num-seqs", str(args.max_num_seqs),
        "--num-decode-steps", str(args.num_decode_steps),
        "--block-size", str(args.block_size),
        "--kv-cache-dtype", args.kv_cache_dtype,
        "--max-paddings", "4096",
        "--swap-space", "0.05",
        "--disable-log-requests",
    ]
    if args.quantization:
        cmd += ["--quantization", args.quantization]
    if args.num_device_blocks:
        cmd += ["--num-device-blocks-override", str(args.num_device_blocks)]
    if role and role != "mixed":
        cmd += ["--replica-role", role]
    if extra_args:
        cmd += list(extra_args)
    env = dict(os.environ)
    env.setdefault("HF_HUB_OFFLINE", "1")
    log = open(log_path, "wb")
    return subprocess.Popen(cmd, env=env, stdout=log,
                            stderr=subprocess.STDOUT)


def run_fleet(args, model_dir: str, tokenizer) -> dict:
    """The fleet scenario: N generate-protocol replicas behind the
    router, rate sweep through the router, and a per-replica SLO split
    (each replica's own /health/detail SLO summary) next to the router's
    routing counters — the view that shows whether affinity is
    concentrating work and the predictor is balancing it."""
    router_base = f"http://127.0.0.1:{args.port}"
    api_url = router_base + "/generate"
    summary = {"scenario": "fleet", "size": args.size,
               "num_replicas": args.num_replicas,
               "input_len": args.input_len, "output_len": args.output_len,
               "num_prompts": args.num_prompts, "seed": args.seed,
               "max_num_seqs": args.max_num_seqs,
               "quantization": args.quantization,
               "kv_cache_dtype": args.kv_cache_dtype, "results": []}
    replicas = []     # (name, base_url, proc, log_path)
    router_proc = None
    try:
        for i in range(args.num_replicas):
            port = args.replica_base_port + i
            log_path = f"{args.server_log}.replica{i}"
            proc = launch_generate_replica(model_dir, args, port, log_path)
            replicas.append((f"replica-{i}", f"http://127.0.0.1:{port}",
                             proc, log_path))
        for name, base, proc, log_path in replicas:
            wait_healthy(proc, base, args.init_timeout, log_path)

        router_log = args.server_log + ".router"
        router_cmd = [
            sys.executable, "-m", "intellillm_tpu.router.server",
            "--host", "127.0.0.1", "--port", str(args.port),
            "--replica-urls", ",".join(b for _, b, _, _ in replicas),
            "--tokenizer", model_dir,
            "--block-size", str(args.block_size),
            "--health-interval", "1.0",
        ]
        env = dict(os.environ)
        env.setdefault("HF_HUB_OFFLINE", "1")
        log = open(router_log, "wb")
        router_proc = subprocess.Popen(router_cmd, env=env, stdout=log,
                                       stderr=subprocess.STDOUT)
        # Router /health goes 200 once its first poll sees a healthy
        # replica, so this also proves the poll loop works.
        wait_healthy(router_proc, router_base, 120.0, router_log)

        requests = build_requests(args, tokenizer)
        # Warm every replica's compile ladder through the router (two
        # all-at-once passes spread load over the fleet).
        for _ in range(2):
            asyncio.run(run_benchmark("generate", api_url, None, requests,
                                      float("inf")))

        for rate_s in args.rates.split(","):
            rate = float(rate_s)
            elapsed, results = asyncio.run(run_benchmark(
                "generate", api_url, None, requests, rate,
                seed=args.seed))
            m = compute_metrics(results, elapsed)
            m["request_rate"] = rate_s
            summary["results"].append(m)
            print(json.dumps({"serve_bench_fleet_rate": rate_s, **m}),
                  flush=True)

        summary["router"] = {
            "metrics": snapshot_router_metrics(router_base),
            "health_detail": snapshot_health_detail(router_base),
        }
        # Fleet-aggregated alert state from the router: the bench's
        # "no alerts fired" assertion (or the list of what did).
        summary["alerts"] = distill_alerts(snapshot_alerts(router_base))
        # Per-hop latency splits: stitched trace samples from the
        # router's aggregator + each replica's own hop decomposition
        # (slo.hops_ms from its /health/detail).
        summary["trace_attribution"] = snapshot_fleet_traces(router_base)
        per_replica = {}
        for name, base, proc, log_path in replicas:
            detail = snapshot_health_detail(base)
            slo = detail.get("slo") or {}
            per_replica[name] = {
                "base": base,
                "status": detail.get("status"),
                "slo": slo,
                "hops_ms": slo.get("hops_ms"),
                "queue_depths": detail.get("queue_depths"),
                "kv_cache_usage": detail.get("kv_cache_usage"),
                "contention": distill_contention(detail),
                "numerics": distill_numerics(detail),
            }
        summary["per_replica_slo"] = per_replica
        summary["contention"] = {
            name: pr["contention"] for name, pr in per_replica.items()}
        # Fleet output-integrity verdict: the router's canary ledger
        # (suspect replicas, reference digest) + each replica's own
        # sentinel/KV-audit counters.
        fleet_numerics = snapshot_numerics(router_base)
        summary["numerics"] = {
            "canary": fleet_numerics.get("canary"),
            "replicas": {name: pr["numerics"]
                         for name, pr in per_replica.items()},
        }
        print(json.dumps({"serve_bench_fleet": {
            "per_replica_slo": per_replica,
            "router": summary["router"],
            "trace_attribution": summary["trace_attribution"],
            "alerts": summary["alerts"],
        }}), flush=True)
    finally:
        if router_proc is not None:
            router_proc.send_signal(signal.SIGKILL)
            router_proc.wait()
        for _, _, proc, _ in replicas:
            proc.send_signal(signal.SIGKILL)
            proc.wait()

    print(json.dumps({"serve_bench_summary": summary}), flush=True)
    return summary


def _run_role_fleet(args, model_dir, tokenizer, roles, label,
                    base_port) -> dict:
    """Boot one replica per entry in `roles` (passed through as
    --replica-role) behind the router (--replica-roles), run the
    ttft-under-load probe through the router's /generate protocol, and
    return the probe/background split plus the router's fleet
    kv_transfer block and each replica's own transfer counters (bytes
    move engine-side, so an HTTP fleet's byte counts live in the
    replica processes, not the router's)."""
    router_base = f"http://127.0.0.1:{args.port}"
    api_url = router_base + "/generate"
    replicas = []
    router_proc = None
    try:
        for i, role in enumerate(roles):
            port = base_port + i
            log_path = f"{args.server_log}.{label}{i}"
            proc = launch_generate_replica(model_dir, args, port, log_path,
                                           role=role)
            replicas.append((f"{label}-{i}-{role}",
                             f"http://127.0.0.1:{port}", proc, log_path))
        for name, base, proc, log_path in replicas:
            wait_healthy(proc, base, args.init_timeout, log_path)

        router_log = f"{args.server_log}.{label}.router"
        router_cmd = [
            sys.executable, "-m", "intellillm_tpu.router.server",
            "--host", "127.0.0.1", "--port", str(args.port),
            "--replica-urls", ",".join(b for _, b, _, _ in replicas),
            "--replica-roles", ",".join(roles),
            "--tokenizer", model_dir,
            "--block-size", str(args.block_size),
            "--health-interval", "1.0",
        ]
        env = dict(os.environ)
        env.setdefault("HF_HUB_OFFLINE", "1")
        log = open(router_log, "wb")
        router_proc = subprocess.Popen(router_cmd, env=env, stdout=log,
                                       stderr=subprocess.STDOUT)
        wait_healthy(router_proc, router_base, 120.0, router_log)

        requests = build_requests(args, tokenizer)
        # Warm every replica's compile ladder through the router. On the
        # disagg fleet this also seeds the KV registry: the repeat pass
        # turns registry misses into fleet/local hits.
        for _ in range(2):
            asyncio.run(run_benchmark("generate", api_url, None, requests,
                                      float("inf")))

        m = run_ttft_under_load(args, api_url, None, tokenizer, requests,
                                protocol="generate")
        detail = snapshot_health_detail(router_base)
        router_detail = (detail.get("router") or {}) if detail else {}
        per_replica_kv = {}
        per_replica_contention = {}
        per_replica_numerics = {}
        kv_bytes = {"export": 0, "import": 0}
        kv_seconds = {"export": 0.0, "import": 0.0}
        for name, base, proc, log_path in replicas:
            rd = snapshot_health_detail(base) or {}
            kv = rd.get("kv_transfer")
            per_replica_kv[name] = kv
            per_replica_contention[name] = distill_contention(rd)
            per_replica_numerics[name] = distill_numerics(rd)
            if kv:
                for d in ("export", "import"):
                    kv_bytes[d] += (kv.get("bytes_total") or {}).get(d, 0)
                    kv_seconds[d] += (kv.get("seconds_total")
                                      or {}).get(d, 0.0)
        return {
            "label": label,
            "roles": list(roles),
            "probe_ttft_ms": m["probe_ttft_ms"],
            "background_ttft_p99_ms": m["background_ttft_p99_ms"],
            "background_tpot_p99_ms": m["background_tpot_p99_ms"],
            "ttft_under_load": m,
            "router_kv_transfer": router_detail.get("kv_transfer"),
            "decisions": router_detail.get("decisions"),
            "kv_bytes": kv_bytes,
            "kv_seconds": {d: round(s, 6) for d, s in kv_seconds.items()},
            "per_replica_kv": per_replica_kv,
            "contention": per_replica_contention,
            "numerics": per_replica_numerics,
        }
    finally:
        if router_proc is not None:
            router_proc.send_signal(signal.SIGKILL)
            router_proc.wait()
        for _, _, proc, _ in replicas:
            proc.send_signal(signal.SIGKILL)
            proc.wait()


def run_disagg(args, model_dir, tokenizer) -> dict:
    """The disagg scenario: A/B the SAME ttft-under-load workload on
    (a) a disaggregated fleet — 1 prefill-role replica + --num-replicas
    decode-role replicas — and (b) a mixed fleet of equal size
    (--num-replicas + 1 mixed replicas), both behind the router. The
    pair of numbers to watch is the probe's TTFT (prefill interference)
    against the background stream's P99 TPOT (decode purity), next to
    what the isolation costs: KV-transfer bytes/seconds and the fleet
    prefix-cache hit counters (docs/routing.md)."""
    n = args.num_replicas
    disagg = _run_role_fleet(args, model_dir, tokenizer,
                             ["prefill"] + ["decode"] * n, "disagg",
                             args.replica_base_port)
    mixed = _run_role_fleet(args, model_dir, tokenizer,
                            ["mixed"] * (n + 1), "mixed",
                            args.replica_base_port + n + 1)
    comparison = {
        "probe_ttft_ms": {"disagg": disagg["probe_ttft_ms"],
                          "mixed": mixed["probe_ttft_ms"]},
        "background_ttft_p99_ms": {
            "disagg": disagg["background_ttft_p99_ms"],
            "mixed": mixed["background_ttft_p99_ms"]},
        "background_tpot_p99_ms": {
            "disagg": disagg["background_tpot_p99_ms"],
            "mixed": mixed["background_tpot_p99_ms"]},
        "kv_bytes": disagg["kv_bytes"],
        "kv_seconds": disagg["kv_seconds"],
        "cache_hits": (disagg["router_kv_transfer"]
                       or {}).get("cache_hits"),
    }
    summary = {"scenario": "disagg", "size": args.size,
               "num_decode_replicas": n, "seed": args.seed,
               "input_len": args.input_len, "output_len": args.output_len,
               "num_prompts": args.num_prompts,
               "max_num_seqs": args.max_num_seqs,
               "fleets": {"disagg": disagg, "mixed": mixed},
               "contention": {"disagg": disagg.get("contention"),
                              "mixed": mixed.get("contention")},
               "numerics": {"disagg": disagg.get("numerics"),
                            "mixed": mixed.get("numerics")},
               "comparison": comparison}
    print(json.dumps({"serve_bench_disagg": comparison}), flush=True)
    print(json.dumps({"serve_bench_summary": summary}), flush=True)
    return summary


def _make_bench_adapter(model_dir: str, out_dir: str, seed: int,
                        rank: int = 8) -> str:
    """Synthesize a tiny HF-PEFT-style LoRA checkpoint (q/v targets)
    against `model_dir`'s config — the multi-tenant scenario needs N
    distinct adapters, not N distinct base models."""
    import numpy as np
    import safetensors.numpy
    with open(os.path.join(model_dir, "config.json")) as f:
        cfg = json.load(f)
    hidden = cfg["hidden_size"]
    heads = cfg["num_attention_heads"]
    kv_heads = cfg.get("num_key_value_heads") or heads
    head_dim = hidden // heads
    dims = {"q_proj": (hidden, hidden),
            "v_proj": (hidden, kv_heads * head_dim)}
    rng = np.random.RandomState(seed)
    tensors = {}
    for li in range(cfg["num_hidden_layers"]):
        for t, (din, dout) in dims.items():
            base = f"base_model.model.model.layers.{li}.self_attn.{t}"
            tensors[f"{base}.lora_A.weight"] = rng.randn(
                rank, din).astype(np.float32) * 0.01
            tensors[f"{base}.lora_B.weight"] = rng.randn(
                dout, rank).astype(np.float32) * 0.01
    os.makedirs(out_dir, exist_ok=True)
    safetensors.numpy.save_file(
        tensors, os.path.join(out_dir, "adapter_model.safetensors"))
    with open(os.path.join(out_dir, "adapter_config.json"), "w") as f:
        json.dump({"r": rank, "lora_alpha": float(rank),
                   "target_modules": list(dims)}, f)
    return out_dir


def _post_json(base: str, path: str, body: dict, timeout: float = 30.0):
    req = urllib.request.Request(
        base + path, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read().decode(errors="replace"))


async def _tenant_request(session, base: str, tenant: str, prompt: str,
                          max_tokens: int, results: list) -> None:
    """One streamed /generate request measuring client-side TTFT and
    TPOT. `ignore_eos` + a fixed `max_tokens` make the token count
    known, so TPOT = (stream tail time) / (max_tokens - 1) regardless
    of how the server batches chunks."""
    payload = {"prompt": prompt, "tenant": tenant, "stream": True,
               "max_tokens": max_tokens, "ignore_eos": True,
               "temperature": 0.0}
    t0 = time.perf_counter()
    ttft = None
    async with session.post(base + "/generate", json=payload) as resp:
        resp.raise_for_status()
        async for line in resp.content:
            if line.strip() and ttft is None:
                ttft = time.perf_counter() - t0
    latency = time.perf_counter() - t0
    results.append({
        "tenant": tenant,
        "ttft_s": ttft,
        "latency_s": latency,
        "tpot_s": ((latency - ttft) / max(max_tokens - 1, 1)
                   if ttft is not None else None),
    })


async def _mt_phase(base: str, victim_tenants, victim_requests: int,
                    victim_output_len: int, hog_tenant=None,
                    hog_concurrency: int = 0,
                    hog_output_len: int = 0,
                    hog_start_delay: float = 1.5) -> list:
    """One load phase: each victim tenant streams `victim_requests`
    sequential requests (concurrency 1 per tenant — the latency probe)
    while the hog tenant, if any, floods `hog_concurrency` concurrent
    long-output requests. The flood starts `hog_start_delay` seconds
    after the victims — this is the noisy-NEIGHBOR scenario: the
    victims are established tenants when the hog arrives, so the
    fairness pass sees >= 2 present tenants and caps the hog at
    admission. (A hog that floods an EMPTY machine legitimately takes
    every seat — work-conserving fairness never evicts running work;
    see docs/multitenancy.md.) Hog tasks are cancelled once every
    victim finishes (the hog exists to create contention, not to be
    measured); the server aborts the dropped streams."""
    import aiohttp
    results: list = []
    hog_results: list = []
    conn = aiohttp.TCPConnector(limit=0)
    timeout = aiohttp.ClientTimeout(total=6 * 3600)
    async with aiohttp.ClientSession(connector=conn,
                                     timeout=timeout) as session:

        async def hog_request(i):
            await asyncio.sleep(hog_start_delay)
            await _tenant_request(session, base, hog_tenant,
                                  "busy " * 24 + str(i), hog_output_len,
                                  hog_results)

        hog_tasks = [
            asyncio.create_task(hog_request(i))
            for i in range(hog_concurrency)
        ] if hog_tenant else []

        async def victim_stream(tenant, salt):
            for i in range(victim_requests):
                await _tenant_request(
                    session, base, tenant,
                    f"measure {salt} {i} " + "ping " * 12,
                    victim_output_len, results)

        await asyncio.gather(*(victim_stream(t, si)
                               for si, t in enumerate(victim_tenants)))
        for task in hog_tasks:
            task.cancel()
        await asyncio.gather(*hog_tasks, return_exceptions=True)
    return results


def _mt_percentiles(rows, field: str) -> dict:
    vals = sorted(r[field] * 1e3 for r in rows
                  if r.get(field) is not None)
    if not vals:
        return {}
    def pick(q):
        return round(vals[min(len(vals) - 1,
                              max(0, int(q * len(vals) + 0.5) - 1))], 2)
    return {"p50": pick(0.50), "p99": pick(0.99), "n": len(vals)}


def run_multi_tenant(args, model_dir, tokenizer) -> dict:
    """The multi-tenant scenario (docs/multitenancy.md): N LoRA tenants
    on ONE replica — two victim tenants streaming latency-probe
    requests, one hot tenant flooding, plus background tenants so the
    registered adapter count exceeds --max-loras (device-slot churn).
    Phases: (1) victims solo, (2) victims + hog with fairness caps on,
    (3) same contention with --disable-tenant-fairness. The isolation
    verdict is victim TPOT p99 per phase: caps-on should hold within
    ~2x of solo while caps-off degrades unboundedly with hog size."""
    base = f"http://127.0.0.1:{args.port}"
    n = max(3, args.num_tenants)
    adapters = [
        _make_bench_adapter(model_dir,
                            os.path.join(model_dir, f"bench-adapter-{i}"),
                            seed=100 + i)
        for i in range(1, n + 1)
    ]
    tenant_ids = [f"tenant-{i}" for i in range(1, n + 1)]
    hog, victims = tenant_ids[0], tenant_ids[1:3]
    max_loras = max(2, n - 1)   # fewer slots than adapters → churn
    lora_flags = ["--enable-lora", "--max-loras", str(max_loras),
                  "--max-lora-rank", "8",
                  "--max-cpu-loras", str(n + 1)]

    def boot(extra, log_suffix):
        log_path = args.server_log + log_suffix
        proc = launch_generate_replica(model_dir, args, args.port,
                                       log_path,
                                       extra_args=lora_flags + extra)
        wait_healthy(proc, base, args.init_timeout, log_path)
        for i, (tid, path) in enumerate(zip(tenant_ids, adapters)):
            body = {"lora_name": tid, "lora_int_id": i + 1,
                    "lora_local_path": path}
            if tid == hog and args.tenant_hog_share_cap:
                body["token_share_cap"] = args.tenant_hog_share_cap
            _post_json(base, f"/tenants/{tid}/adapter", body)
        # Touch every tenant once: warms the compile ladder and pulls
        # each adapter through the loader before measurement.
        asyncio.run(_mt_phase(base, tenant_ids, 1,
                              args.victim_output_len))
        return proc

    summary = {"scenario": "multi-tenant", "size": args.size,
               "seed": args.seed,
               "num_tenants": n, "max_loras": max_loras,
               "hog": hog, "victims": victims,
               "hog_concurrency": args.hog_concurrency,
               "hog_output_len": args.hog_output_len,
               "victim_requests": args.victim_requests,
               "victim_output_len": args.victim_output_len,
               "tenant_hog_share_cap": args.tenant_hog_share_cap,
               "max_num_seqs": args.max_num_seqs}
    phases = {}
    proc = boot([], ".mt-fair")
    try:
        solo = asyncio.run(_mt_phase(
            base, victims, args.victim_requests, args.victim_output_len))
        phases["victim_solo"] = solo
        caps_on = asyncio.run(_mt_phase(
            base, victims, args.victim_requests, args.victim_output_len,
            hog_tenant=hog, hog_concurrency=args.hog_concurrency,
            hog_output_len=args.hog_output_len,
            hog_start_delay=args.hog_start_delay))
        phases["contention_caps_on"] = caps_on
        detail = snapshot_health_detail(base)
        summary["tenants_caps_on"] = detail.get("tenants")
        contention = {"caps_on": distill_contention(detail)}
        summary["numerics"] = distill_numerics(detail)
        summary["alerts_caps_on"] = distill_alerts(snapshot_alerts(base))
    finally:
        proc.send_signal(signal.SIGKILL)
        proc.wait()

    proc = boot(["--disable-tenant-fairness"], ".mt-unfair")
    try:
        caps_off = asyncio.run(_mt_phase(
            base, victims, args.victim_requests, args.victim_output_len,
            hog_tenant=hog, hog_concurrency=args.hog_concurrency,
            hog_output_len=args.hog_output_len,
            hog_start_delay=args.hog_start_delay))
        phases["contention_caps_off"] = caps_off
        detail = snapshot_health_detail(base)
        summary["tenants_caps_off"] = detail.get("tenants")
        contention["caps_off"] = distill_contention(detail)
    finally:
        proc.send_signal(signal.SIGKILL)
        proc.wait()

    per_phase = {}
    for phase, rows in phases.items():
        per_phase[phase] = {
            "tpot_ms": _mt_percentiles(rows, "tpot_s"),
            "ttft_ms": _mt_percentiles(rows, "ttft_s"),
            "per_tenant_tpot_ms": {
                t: _mt_percentiles([r for r in rows if r["tenant"] == t],
                                   "tpot_s")
                for t in sorted({r["tenant"] for r in rows})},
        }
    summary["victim_latency"] = per_phase
    summary["contention"] = contention

    def ratio(a, b):
        return (round(a / b, 3)
                if a is not None and b else None)
    solo_p99 = (per_phase.get("victim_solo", {})
                .get("tpot_ms", {}).get("p99"))
    on_p99 = (per_phase.get("contention_caps_on", {})
              .get("tpot_ms", {}).get("p99"))
    off_p99 = (per_phase.get("contention_caps_off", {})
               .get("tpot_ms", {}).get("p99"))
    stats_on = ((summary.get("tenants_caps_on") or {}).get("stats")
                or {})
    churn = {t: {"loads": (stats_on.get(t) or {}).get("adapter_loads"),
                 "evictions": (stats_on.get(t)
                               or {}).get("adapter_evictions"),
                 "deferred_tokens": (stats_on.get(t)
                                     or {}).get("deferred_tokens")}
             for t in tenant_ids}
    isolation = {
        "victim_tpot_p99_ms": {"solo": solo_p99, "caps_on": on_p99,
                               "caps_off": off_p99},
        "caps_on_vs_solo": ratio(on_p99, solo_p99),
        "caps_off_vs_solo": ratio(off_p99, solo_p99),
        "caps_off_vs_caps_on": ratio(off_p99, on_p99),
        "isolation_holds_2x": (on_p99 is not None and solo_p99
                               and on_p99 <= 2.0 * solo_p99),
        "adapter_churn": churn,
    }
    summary["isolation"] = isolation
    print(json.dumps({"serve_bench_multitenant": isolation}), flush=True)
    print(json.dumps({"serve_bench_summary": summary}), flush=True)
    return summary


def _compare_policies(args, model_dir, tokenizer, policies) -> dict:
    """Run the ttft-under-load scenario once per scheduling policy (one
    server lifecycle each) and print an SLO comparison block — the
    FCFS-vs-SJF view docs/scheduling.md describes."""
    rows = {}
    summaries = {}
    for policy in policies:
        s = run_single(args, model_dir, tokenizer, scheduling_policy=policy)
        summaries[policy] = s
        result = s["results"][0]
        slo = s.get("slo") or {}
        rows[policy] = {
            "probe_ttft_ms": result["probe_ttft_ms"],
            "background_ttft_p99_ms": result["background_ttft_p99_ms"],
            "background_tpot_p99_ms": result["background_tpot_p99_ms"],
            "queue_wait_p99_ms": (slo.get("queue_wait_ms") or {}).get("p99"),
            "goodput_ratio": slo.get("goodput_ratio"),
        }
    block = {"scenario": args.scenario, "policies": rows,
             "seed": args.seed,
             "sjf_starvation_s": args.sjf_starvation_s}
    base_row = rows.get("fcfs")
    if base_row is not None:
        for policy, row in rows.items():
            if policy == "fcfs":
                continue
            for key in ("probe_ttft_ms", "background_ttft_p99_ms",
                        "background_tpot_p99_ms"):
                if (row.get(key) is not None
                        and base_row.get(key) is not None):
                    row[f"{key}_delta_vs_fcfs"] = round(
                        row[key] - base_row[key], 1)
    if args.sjf_starvation_s is not None:
        deadline_ms = args.sjf_starvation_s * 1e3
        for row in rows.values():
            qw = row.get("queue_wait_p99_ms")
            row["queue_wait_under_deadline"] = (
                qw is not None and qw < deadline_ms)
    print(json.dumps({"serve_bench_policy_comparison": block}), flush=True)
    return {"policy_comparison": block, "summaries": summaries}


def _compare_spec(args, model_dir, tokenizer) -> dict:
    """Run the rate sweep twice — target-only, then with the draft model
    speculating — one server lifecycle each, and print a spec on/off
    comparison block. Greedy spec emits the target's exact stream, so
    the delta is pure serving throughput/latency, not a quality trade
    (with dummy weights acceptance is ~0: this measures the overhead
    floor; real checkpoints measure the win)."""
    spec_dir = args._spec_model_dir
    args._spec_model_dir = None
    baseline = run_single(args, model_dir, tokenizer)
    args._spec_model_dir = spec_dir
    spec = run_single(args, model_dir, tokenizer)

    def _row(summary):
        results = summary.get("results") or []
        rates = {}
        for m in results:
            rates[m.get("request_rate", "?")] = {
                "output_tok_s": m.get("output_tok_s"),
                "ttft_p99_ms": (m.get("ttft_percentiles_ms")
                                or {}).get("p99"),
                "tpot_p99_ms": (m.get("tpot_percentiles_ms")
                                or {}).get("p99"),
            }
        return rates

    base_rates, spec_rates = _row(baseline), _row(spec)
    for rate, row in spec_rates.items():
        base = base_rates.get(rate) or {}
        if (row.get("output_tok_s") is not None
                and base.get("output_tok_s")):
            row["output_tok_s_ratio_vs_off"] = round(
                row["output_tok_s"] / base["output_tok_s"], 3)
    block = {
        "seed": args.seed,
        "num_speculative_tokens": args.num_speculative_tokens,
        "spec_k_min": args.spec_k_min,
        "spec_k_max": args.spec_k_max,
        "spec_off": base_rates,
        "spec_on": spec_rates,
        # Acceptance/K/waste as the spec run ended (from /health/detail).
        "spec_stats": spec.get("spec"),
    }
    print(json.dumps({"serve_bench_spec_comparison": block}), flush=True)
    return {"spec_comparison": block,
            "summaries": {"spec_off": baseline, "spec_on": spec}}


def main(args) -> dict:
    from transformers import AutoTokenizer

    model_dir = args.model_dir or tempfile.mkdtemp(prefix="serve-bench-")
    if not os.path.exists(os.path.join(model_dir, "config.json")):
        # Only materialize the dummy checkpoint into an EMPTY dir — never
        # clobber an existing checkpoint passed via --model-dir.
        save_dummy_checkpoint(f"dummy:{args.size}", model_dir)
    tokenizer = AutoTokenizer.from_pretrained(model_dir)

    # Draft checkpoint for --speculative-size: its own dir, same dummy
    # materialization rule (vocab must match the target's, which holds
    # for the shared DUMMY_SIZES table).
    args._spec_model_dir = None
    if args.speculative_size:
        spec_dir = tempfile.mkdtemp(prefix="serve-bench-draft-")
        save_dummy_checkpoint(f"dummy:{args.speculative_size}", spec_dir)
        args._spec_model_dir = spec_dir

    summary = _dispatch(args, model_dir, tokenizer)
    if args.summary_out:
        # Machine-readable snapshot for `python -m
        # intellillm_tpu.tools.wdiff` (obs/diff.py) — compare two of
        # these to flag regressions between runs.
        with open(args.summary_out, "w") as f:
            json.dump(summary, f, indent=2, sort_keys=True, default=str)
            f.write("\n")
    return summary


def _dispatch(args, model_dir, tokenizer) -> dict:
    if args.scenario == "fleet":
        return run_fleet(args, model_dir, tokenizer)

    if args.scenario == "disagg":
        return run_disagg(args, model_dir, tokenizer)

    if args.scenario == "multi-tenant":
        return run_multi_tenant(args, model_dir, tokenizer)

    if args.scenario == "replay":
        return run_replay(args, model_dir, tokenizer)

    if args.scenario == "diurnal":
        return run_diurnal(args, model_dir, tokenizer)

    if args.compare_spec:
        if not args._spec_model_dir:
            raise SystemExit("--compare-spec requires --speculative-size")
        if args.scenario != "rate-sweep":
            raise SystemExit(
                "--compare-spec only supports --scenario rate-sweep")
        return _compare_spec(args, model_dir, tokenizer)

    policies = [p.strip() for p in (args.scheduling_policy or "").split(",")
                if p.strip()]
    if len(policies) > 1:
        if args.scenario != "ttft-under-load":
            raise SystemExit(
                "--scheduling-policy accepts a comma-separated comparison "
                "axis only with --scenario ttft-under-load")
        return _compare_policies(args, model_dir, tokenizer, policies)
    return run_single(args, model_dir, tokenizer,
                      scheduling_policy=policies[0] if policies else None)


def run_single(args, model_dir, tokenizer, scheduling_policy=None) -> dict:
    proc = launch_server(model_dir, args,
                         scheduling_policy=scheduling_policy)
    base = f"http://127.0.0.1:{args.port}"
    api_url = base + "/v1/completions"
    model_name = f"dummy-{args.size}"
    summary = {"size": args.size, "input_len": args.input_len,
               "output_len": args.output_len,
               "num_prompts": args.num_prompts, "seed": args.seed,
               "max_num_seqs": args.max_num_seqs,
               "num_decode_steps": args.num_decode_steps,
               "quantization": args.quantization,
               "scheduling_policy": scheduling_policy or "fcfs",
               "sjf_starvation_s": args.sjf_starvation_s,
               "kv_cache_dtype": args.kv_cache_dtype, "results": []}
    try:
        wait_healthy(proc, base, args.init_timeout, args.server_log)

        requests = build_requests(args, tokenizer)
        # Warm-up: touch the *whole* (batch-bucket x block-width-bucket)
        # ladder before measuring. Trickled arrivals hit small batch
        # buckets (1, 2, 4, ...) that an all-at-once burst never
        # exercises, and mid-load concurrency (e.g. a steady 8 req/s
        # holding ~64 running) pairs those buckets with WIDER block
        # tables than short warm contexts produce — each combo is a
        # separate XLA executable, and a first-compile mid-measurement
        # stalls serving for tens of seconds (measured: one cold
        # (bs=64, width=32) decode compile collapsed a rate-8 run to
        # 188 tok/s). Warm outputs run past the first width-bucket
        # boundary (16 blocks) to cover both widths; the persistent
        # compile cache makes later boots fast.
        warm_out = max(args.output_len,
                       16 * args.block_size + 48 - args.input_len)
        # Never exceed the context limit (the server would reject the
        # request and abort the whole warm-up).
        warm_out = max(1, min(warm_out,
                              args.max_model_len - args.input_len - 1))
        warm = [(p, pl, warm_out) for p, pl, _ in requests]
        n_warm = 1
        while n_warm <= min(args.max_num_seqs, len(warm)):
            asyncio.run(run_benchmark("openai", api_url, model_name,
                                      warm[:n_warm], float("inf")))
            n_warm *= 2
        asyncio.run(run_benchmark(
            "openai", api_url, model_name,
            warm[:max(4, min(args.max_num_seqs, len(warm)))],
            float("inf")))

        if args.scenario == "ttft-under-load":
            m = run_ttft_under_load(args, api_url, model_name, tokenizer,
                                    requests)
            summary["results"].append(m)
            print(json.dumps({"serve_bench_ttft_under_load": m}),
                  flush=True)
        else:
            for rate_s in args.rates.split(","):
                rate = float(rate_s)
                elapsed, results = asyncio.run(run_benchmark(
                    "openai", api_url, model_name, requests, rate,
                    seed=args.seed))
                m = compute_metrics(results, elapsed)
                m["request_rate"] = rate_s
                summary["results"].append(m)
                print(json.dumps({"serve_bench_rate": rate_s, **m}),
                      flush=True)
        summary["observability"] = snapshot_observability(base)
        detail = snapshot_health_detail(base)
        # Structured warm-up outcome from the boot timeline: compiled
        # executable count + wall seconds, plus the machine-checked
        # "<30s warm-up" boot criterion.
        boot = detail.get("boot") or {}
        warmup = boot.get("warmup")
        summary["boot"] = boot
        summary["warmup_compile"] = (
            {**warmup, "under_30s": warmup.get("seconds", 1e9) < 30.0}
            if warmup else None)
        summary["slo"] = detail.get("slo") or {}
        summary["predictor"] = detail.get("predictor")
        # Spec-decode stats (acceptance rate, current K, verify waste)
        # from /health/detail; None when serving without a draft model.
        summary["spec"] = detail.get("spec")
        summary["device_telemetry"] = distill_device_telemetry(detail)
        summary["efficiency"] = snapshot_efficiency(base)
        summary["kernels"] = snapshot_kernels(base)
        summary["contention"] = distill_contention(detail)
        summary["numerics"] = distill_numerics(detail)
        summary["alerts"] = distill_alerts(snapshot_alerts(base))
    finally:
        proc.send_signal(signal.SIGKILL)
        proc.wait()

    print(json.dumps({"serve_bench_summary": summary}), flush=True)
    return summary


def make_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="Boot OpenAI server + sweep serving request rates.")
    p.add_argument("--size", type=str, default="7b",
                   help="dummy model size spec (see common.DUMMY_SIZES)")
    p.add_argument("--model-dir", type=str, default=None,
                   help="reuse an existing checkpoint dir")
    p.add_argument("--port", type=int, default=8077)
    p.add_argument("--rates", type=str, default="2,4,8,inf",
                   help="comma-separated requests/s (inf = all at once)")
    p.add_argument("--num-prompts", type=int, default=100)
    p.add_argument("--input-len", type=int, default=128)
    p.add_argument("--output-len", type=int, default=128)
    p.add_argument("--dataset", type=str, default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--max-model-len", type=int, default=512)
    p.add_argument("--max-num-seqs", type=int, default=96)
    p.add_argument("--num-decode-steps", type=int, default=32)
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--num-device-blocks", type=int, default=None)
    p.add_argument("--kv-cache-dtype", type=str, default="auto")
    p.add_argument("--quantization", type=str, default=None)
    p.add_argument("--init-timeout", type=float, default=1800.0)
    p.add_argument("--server-log", type=str,
                   default="/tmp/serve_bench_server.log")
    p.add_argument("--scenario", type=str, default="rate-sweep",
                   choices=["rate-sweep", "ttft-under-load", "fleet",
                            "disagg", "multi-tenant", "replay",
                            "diurnal"],
                   help="rate-sweep: Poisson sweep over --rates (the "
                        "default). ttft-under-load: start --num-prompts "
                        "short-prompt requests at once (steady decode "
                        "stream), inject one long-prompt probe after "
                        "--probe-delay, and report the probe's TTFT plus "
                        "the stream's P99 TPOT — the interference pair "
                        "chunked prefill is designed to improve. fleet: "
                        "boot --num-replicas demo servers behind the "
                        "multi-replica router, sweep --rates through the "
                        "router, and report per-replica SLO splits plus "
                        "the router's routing counters. disagg: A/B the "
                        "ttft-under-load workload on a disaggregated "
                        "fleet (1 prefill + --num-replicas decode "
                        "replicas) vs an equal-size mixed fleet, and "
                        "report the probe-TTFT/background-TPOT split "
                        "plus KV-transfer bytes/seconds and fleet "
                        "prefix-cache hit counts. multi-tenant: "
                        "--num-tenants LoRA tenants on one replica with "
                        "one hot tenant flooding; reports victim-tenant "
                        "TPOT p99 solo vs contention with fairness caps "
                        "on and off, per-tenant SLO splits, and adapter "
                        "churn counters (docs/multitenancy.md). "
                        "replay: re-issue a captured IWL1 workload "
                        "(--workload, from /debug/workload?format=iwl "
                        "or a rotated workload.jsonl) with the original "
                        "inter-arrival gaps; --replay-repeat N runs the "
                        "stream N times against one boot and checks the "
                        "server-side re-captures match (determinism). "
                        "diurnal: synthesize a seeded day-in-the-life "
                        "IWL1 stream (flash crowds, heavy-tailed "
                        "lengths, adapter churn) and replay it; "
                        "--emit-only just writes the file "
                        "(docs/observability.md).")
    p.add_argument("--num-replicas", type=int, default=2,
                   help="fleet scenario: engine replicas to launch; "
                        "disagg scenario: decode replicas per fleet")
    p.add_argument("--replica-base-port", type=int, default=8300,
                   help="fleet/disagg scenarios: replica i listens on "
                        "base+i")
    p.add_argument("--probe-input-len", type=int, default=None,
                   help="probe prompt length for ttft-under-load "
                        "(default: max-model-len - probe-output-len - 1)")
    p.add_argument("--probe-output-len", type=int, default=16)
    p.add_argument("--probe-delay", type=float, default=2.0,
                   help="seconds after the background burst before the "
                        "probe is sent")
    p.add_argument("--scheduling-policy", type=str, default=None,
                   help="pass --scheduling-policy to the server (fcfs | "
                        "sjf | sjf_remaining). With --scenario "
                        "ttft-under-load a comma-separated list (e.g. "
                        "'fcfs,sjf_remaining') runs the scenario once per "
                        "policy and prints an SLO comparison block")
    p.add_argument("--sjf-starvation-s", type=float, default=None,
                   help="pass --sjf-starvation-s to the server (SJF "
                        "aging deadline, seconds)")
    p.add_argument("--predictor-path", type=str, default=None,
                   help="pass --predictor-path to the server "
                        "(length-predictor checkpoint)")
    p.add_argument("--enable-chunked-prefill", action="store_true",
                   default=True,
                   help="DEPRECATED no-op, kept for script "
                        "compatibility: chunked prefill is the server "
                        "default; use --disable-chunked-prefill to turn "
                        "it off")
    p.add_argument("--disable-chunked-prefill", action="store_true",
                   help="pass --disable-chunked-prefill to the server "
                        "(whole-prompt single-chunk admission)")
    p.add_argument("--max-num-batched-tokens", type=int, default=None,
                   help="pass --max-num-batched-tokens to the server "
                        "(per-step token budget; with chunked prefill "
                        "this caps mixed-step compute)")
    p.add_argument("--speculative-size", type=str, default=None,
                   help="dummy draft model size (see common.DUMMY_SIZES); "
                        "materializes a draft checkpoint and passes "
                        "--speculative-model to the server")
    p.add_argument("--num-speculative-tokens", type=int, default=4,
                   help="draft length K passed to the server with "
                        "--speculative-size")
    p.add_argument("--spec-k-min", type=int, default=None,
                   help="pass --spec-k-min to the server (adaptive-K "
                        "band floor)")
    p.add_argument("--spec-k-max", type=int, default=None,
                   help="pass --spec-k-max to the server (adaptive-K "
                        "band ceiling)")
    p.add_argument("--compare-spec", action="store_true",
                   help="with --speculative-size: run the rate sweep "
                        "twice (spec off, then on) and print a "
                        "serve_bench_spec_comparison block")
    p.add_argument("--num-tenants", type=int, default=4,
                   help="multi-tenant scenario: LoRA tenants to "
                        "register (adapters synthesized per tenant; "
                        "--max-loras is set to num-tenants - 1 so slot "
                        "churn is exercised)")
    p.add_argument("--hog-concurrency", type=int, default=40,
                   help="multi-tenant scenario: concurrent long-output "
                        "requests the hot tenant keeps in flight")
    p.add_argument("--hog-output-len", type=int, default=160,
                   help="multi-tenant scenario: output tokens per hog "
                        "request")
    p.add_argument("--hog-start-delay", type=float, default=1.5,
                   help="multi-tenant scenario: seconds after the "
                        "victim probes start before the hog floods "
                        "(victims must be resident for admission "
                        "fairness to see two tenants)")
    p.add_argument("--victim-requests", type=int, default=5,
                   help="multi-tenant scenario: sequential probe "
                        "requests per victim tenant per phase")
    p.add_argument("--victim-output-len", type=int, default=32,
                   help="multi-tenant scenario: output tokens per "
                        "victim probe request")
    p.add_argument("--tenant-hog-share-cap", type=float, default=0.2,
                   help="multi-tenant scenario: token_share_cap "
                        "registered for the hot tenant (0 disables)")
    p.add_argument("--workload", type=str, default=None,
                   help="replay scenario: IWL1 workload file to "
                        "re-issue (capture one from "
                        "/debug/workload?format=iwl)")
    p.add_argument("--speed", type=float, default=1.0,
                   help="replay scenario: time-compression factor for "
                        "recorded inter-arrival gaps (2.0 = replay "
                        "twice as fast)")
    p.add_argument("--replay-repeat", type=int, default=1,
                   help="replay scenario: run the stream N times "
                        "against one booted server and report whether "
                        "the server-side workload re-captures match "
                        "(the determinism check)")
    p.add_argument("--workload-out", type=str, default=None,
                   help="diurnal: where to write the synthesized IWL1 "
                        "stream (default /tmp/serve_bench_diurnal"
                        ".iwl.jsonl); replay: also save the last "
                        "server-side re-capture here")
    p.add_argument("--emit-only", action="store_true",
                   help="diurnal scenario: write the synthesized IWL1 "
                        "file and exit without booting a server")
    p.add_argument("--summary-out", type=str, default=None,
                   help="write the final summary dict as JSON to this "
                        "path (feed two of these to python -m "
                        "intellillm_tpu.tools.wdiff)")
    p.add_argument("--diurnal-duration", type=float, default=30.0,
                   help="diurnal scenario: seconds of simulated wall "
                        "time the synthesized arrivals span")
    p.add_argument("--diurnal-bursts", type=int, default=2,
                   help="diurnal scenario: number of gaussian flash "
                        "crowds mixed into the baseline arrival stream")
    return p


if __name__ == "__main__":
    main(make_arg_parser().parse_args())
