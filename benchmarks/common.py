"""Shared helpers for the benchmark harnesses.

Role parity: the argument/metric surface of the reference
`benchmarks/benchmark_{latency,throughput,serving}.py`. The TPU twist: in
addition to real checkpoints, every harness accepts `--model dummy:7b`
style specs that build a Llama-shaped engine with random weights (no
checkpoint downloads in the TPU environment; throughput is
weight-value-independent).
"""
from __future__ import annotations

import json
import random
from typing import List, Optional, Tuple

import numpy as np

# (hidden, inter, layers, heads, kv_heads, vocab)
DUMMY_SIZES = {
    "7b": (4096, 11008, 32, 32, 32, 32000),
    "13b": (5120, 13824, 40, 40, 40, 32000),
    "1b": (2048, 5632, 22, 32, 4, 32000),
    "tiny": (256, 512, 2, 8, 8, 1024),
}


def is_dummy(model: str) -> bool:
    return model.startswith("dummy:")


def dummy_hf_config(model: str):
    from transformers import LlamaConfig
    size = model.split(":", 1)[1]
    hidden, inter, layers, heads, kv_heads, vocab = DUMMY_SIZES[size]
    return LlamaConfig(
        vocab_size=vocab, hidden_size=hidden, intermediate_size=inter,
        num_hidden_layers=layers, num_attention_heads=heads,
        num_key_value_heads=kv_heads, max_position_embeddings=4096,
        tie_word_embeddings=False)


def build_llm(args):
    """Build an offline `LLM` from harness args (real checkpoint or
    dummy:SIZE spec)."""
    from intellillm_tpu.config import (CacheConfig, ModelConfig,
                                       ParallelConfig, SchedulerConfig)
    from intellillm_tpu.engine.llm_engine import LLMEngine
    from intellillm_tpu.entrypoints.llm import LLM

    if not is_dummy(args.model):
        return LLM(
            model=args.model,
            tokenizer=getattr(args, "tokenizer", None),
            quantization=getattr(args, "quantization", None),
            tensor_parallel_size=getattr(args, "tensor_parallel_size", 1),
            dtype=getattr(args, "dtype", "auto"),
            max_model_len=getattr(args, "max_model_len", None),
            enforce_eager=getattr(args, "enforce_eager", False),
            kv_cache_dtype=getattr(args, "kv_cache_dtype", "auto"),
            trust_remote_code=getattr(args, "trust_remote_code", False),
            max_num_seqs=getattr(args, "max_num_seqs", 256),
            num_device_blocks_override=getattr(args, "num_device_blocks",
                                               None),
        )

    model_config = ModelConfig.from_hf_config(
        dummy_hf_config(args.model),
        dtype=(args.dtype if getattr(args, "dtype", "auto") != "auto"
               else "bfloat16"),
        max_model_len=getattr(args, "max_model_len", None) or 2048,
        load_format="dummy",
        quantization=getattr(args, "quantization", None))
    cache_config = CacheConfig(
        block_size=16,
        num_device_blocks_override=getattr(args, "num_device_blocks", None),
        swap_space_gib=1.0,
        cache_dtype=getattr(args, "kv_cache_dtype", "auto"))
    scheduler_config = SchedulerConfig(
        max_num_batched_tokens=max(2048, model_config.max_model_len),
        max_num_seqs=getattr(args, "max_num_seqs", 256),
        max_model_len=model_config.max_model_len,
        max_paddings=4096)
    engine = LLMEngine(model_config, cache_config, ParallelConfig(),
                       scheduler_config, log_stats=False,
                       skip_tokenizer_init=True)
    llm = LLM.__new__(LLM)
    llm.llm_engine = engine
    from intellillm_tpu.utils import Counter
    llm.request_counter = Counter()
    return llm


def save_dummy_checkpoint(model_spec: str, out_dir: str,
                          tokenizer_vocab: Optional[int] = None) -> str:
    """Materialize a `dummy:SIZE` spec as an on-disk checkpoint dir the
    servers can boot with `--load-format dummy`: the Llama config.json
    plus a self-contained word-level tokenizer (no hub access; decode →
    encode roundtrips exactly, so client- and server-side token counts
    agree in `benchmark_serving.py`)."""
    from tokenizers import Tokenizer
    from tokenizers.models import WordLevel
    from tokenizers.pre_tokenizers import Whitespace
    from transformers import PreTrainedTokenizerFast

    cfg = dummy_hf_config(model_spec)
    cfg.save_pretrained(out_dir)
    # Cover the full model vocab so detokenizing dummy-weight samples
    # (uniform over vocab_size ids) never hits an out-of-range token.
    if tokenizer_vocab is None:
        tokenizer_vocab = cfg.vocab_size
    vocab = {"<pad>": 0, "</s>": 1, "<unk>": 2}
    for i in range(tokenizer_vocab - len(vocab)):
        vocab[f"w{i:05d}"] = len(vocab)
    tok = Tokenizer(WordLevel(vocab, unk_token="<unk>"))
    tok.pre_tokenizer = Whitespace()
    PreTrainedTokenizerFast(
        tokenizer_object=tok, pad_token="<pad>", eos_token="</s>",
        unk_token="<unk>").save_pretrained(out_dir)
    return out_dir


def sample_requests(
    dataset_path: Optional[str],
    num_prompts: int,
    tokenizer,
    input_len: int,
    output_len: int,
    vocab_size: int,
    seed: int = 0,
) -> List[Tuple[List[int], int]]:
    """(prompt_token_ids, output_len) pairs: from a ShareGPT-format json
    when given (reference benchmark_throughput.py sample_requests role),
    else synthetic random-token prompts."""
    rng = np.random.default_rng(seed)
    if dataset_path is None:
        return [
            (rng.integers(0, vocab_size, size=input_len).tolist(),
             output_len) for _ in range(num_prompts)
        ]

    with open(dataset_path) as f:
        dataset = json.load(f)
    # ShareGPT: take the first two turns (prompt, completion).
    dataset = [d for d in dataset if len(d.get("conversations", [])) >= 2]
    random.Random(seed).shuffle(dataset)
    requests: List[Tuple[List[int], int]] = []
    for d in dataset:
        prompt = d["conversations"][0]["value"]
        completion = d["conversations"][1]["value"]
        prompt_ids = tokenizer.encode(prompt)
        completion_len = len(tokenizer.encode(completion))
        if len(prompt_ids) < 4 or completion_len < 4:
            continue
        if len(prompt_ids) > 1024 or len(prompt_ids) + completion_len > 2048:
            continue
        requests.append((prompt_ids, completion_len))
        if len(requests) == num_prompts:
            break
    return requests


def percentiles(values: List[float], ps=(50, 90, 99)) -> dict:
    if not values:
        return {f"p{p}": float("nan") for p in ps}
    arr = np.asarray(values)
    return {f"p{p}": float(np.percentile(arr, p)) for p in ps}
