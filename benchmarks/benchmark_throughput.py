"""Offline generation throughput.

Role parity: reference `benchmarks/benchmark_throughput.py` (ShareGPT or
synthetic workload, requests/s + tokens/s, optional HF baseline backend).

Usage:
    python benchmarks/benchmark_throughput.py --model dummy:7b \
        --num-prompts 64 --input-len 128 --output-len 128
    python benchmarks/benchmark_throughput.py --model /path/llama \
        --dataset /path/sharegpt.json --num-prompts 200
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from benchmarks.common import (build_llm, is_dummy,  # noqa: E402
                               sample_requests)


def run_intellillm(args, requests):
    from intellillm_tpu.sampling_params import SamplingParams

    llm = build_llm(args)
    engine = llm.llm_engine
    for i, (prompt_ids, output_len) in enumerate(requests):
        sampling_params = SamplingParams(
            n=args.n,
            temperature=0.0 if args.use_beam_search else 1.0,
            top_p=1.0,
            use_beam_search=args.use_beam_search,
            ignore_eos=True,
            max_tokens=output_len,
        )
        engine.add_request(str(i), None, sampling_params,
                           prompt_token_ids=prompt_ids)
    start = time.perf_counter()
    llm._run_engine(use_tqdm=not args.no_tqdm)
    return time.perf_counter() - start


def run_hf(args, requests):
    """HF transformers greedy loop (reference run_hf role) — baseline for
    small models on CPU/TPU-host."""
    import torch
    from transformers import AutoModelForCausalLM

    model = AutoModelForCausalLM.from_pretrained(args.model)
    model.eval()
    start = time.perf_counter()
    for prompt_ids, output_len in requests:
        input_ids = torch.tensor([prompt_ids])
        with torch.no_grad():
            model.generate(input_ids, do_sample=False,
                           min_new_tokens=output_len,
                           max_new_tokens=output_len)
    return time.perf_counter() - start


def main(args):
    tokenizer = None
    vocab_size = 32000
    if is_dummy(args.model):
        from benchmarks.common import dummy_hf_config
        vocab_size = dummy_hf_config(args.model).vocab_size
        assert args.dataset is None, "--dataset needs a real tokenizer"
    else:
        from transformers import AutoTokenizer
        tokenizer = AutoTokenizer.from_pretrained(args.model)
        vocab_size = len(tokenizer)

    requests = sample_requests(args.dataset, args.num_prompts, tokenizer,
                               args.input_len, args.output_len, vocab_size,
                               args.seed)
    if args.backend == "intellillm":
        elapsed = run_intellillm(args, requests)
    else:
        elapsed = run_hf(args, requests)

    total_tokens = sum(len(p) + o for p, o in requests)
    out_tokens = sum(o for _, o in requests)
    print(f"Throughput: {len(requests) / elapsed:.2f} requests/s, "
          f"{total_tokens / elapsed:.1f} total tok/s, "
          f"{out_tokens / elapsed:.1f} output tok/s")


if __name__ == "__main__":
    from intellillm_tpu.utils import apply_platform_override
    apply_platform_override()
    parser = argparse.ArgumentParser(description="Benchmark throughput.")
    parser.add_argument("--backend", type=str, default="intellillm",
                        choices=["intellillm", "hf"])
    parser.add_argument("--model", type=str, default="dummy:7b")
    parser.add_argument("--tokenizer", type=str, default=None)
    parser.add_argument("--dataset", type=str, default=None,
                        help="ShareGPT-format json; synthetic when absent")
    parser.add_argument("--num-prompts", type=int, default=64)
    parser.add_argument("--input-len", type=int, default=128)
    parser.add_argument("--output-len", type=int, default=128)
    parser.add_argument("--n", type=int, default=1)
    parser.add_argument("--use-beam-search", action="store_true")
    parser.add_argument("--quantization", "-q", type=str, default=None)
    parser.add_argument("--tensor-parallel-size", "-tp", type=int, default=1)
    parser.add_argument("--dtype", type=str, default="auto")
    parser.add_argument("--max-model-len", type=int, default=None)
    parser.add_argument("--max-num-seqs", type=int, default=256)
    parser.add_argument("--num-device-blocks", type=int, default=None)
    parser.add_argument("--kv-cache-dtype", type=str, default="auto")
    parser.add_argument("--enforce-eager", action="store_true")
    parser.add_argument("--trust-remote-code", action="store_true")
    parser.add_argument("--no-tqdm", action="store_true")
    parser.add_argument("--seed", type=int, default=0)
    main(parser.parse_args())
