"""Speculative-decoding throughput bracketing on dummy weights.

Real acceptance rates need real checkpoints (random draft/target weights
never agree), so this harness brackets the machinery instead
(reference role: `vllm/worker/spec_decode/` — which the reference never
measured either, since it never wired the scaffold):

  - floor  (a~0):  7B target + 1B draft, real acceptance — every round
                   pays draft K+1 + verify K+1 and emits ~1 token
  - ceiling (a=1): same pair with INTELLILLM_SPEC_FORCE_ACCEPT=1 —
                   every round emits K+1 tokens
  - baseline:      plain 7B fused decode at the same K
  - adaptive:      force-accept with a [1..K] band and a fast controller
                   clock — exercises the K-ladder warm-up plus runtime K
                   transitions under load (the floor/ceiling modes pin K)

Prints one JSON line per mode. Usage:
    python benchmarks/spec_bench.py [--k 4] [--bs 32] [--out 64]
                                    [--modes baseline,floor,ceiling,adaptive]
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_MODE_TIMEOUT_S = 2400.0


def _run_bench_child(env: dict, timeout_s: float):
    """Run one bench.py mode in its OWN process group; on timeout SIGKILL
    the whole group (same hardening as bench.py's backend probe: the TPU
    runtime forks helpers that hold the device and the stderr pipe, so
    `subprocess.run(timeout=...)` killing only the direct child leaves
    the follow-up mode hanging on a wedged device). Returns
    (returncode, stdout, stderr); raises TimeoutExpired carrying the
    output produced before the kill."""
    proc = subprocess.Popen(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "..", "bench.py")],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, start_new_session=True)
    try:
        out, err = proc.communicate(timeout=timeout_s)
        return proc.returncode, out, err
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        try:
            out, err = proc.communicate(timeout=10.0)
        except subprocess.TimeoutExpired:
            out, err = "", ""
        raise subprocess.TimeoutExpired(
            cmd=proc.args, timeout=timeout_s, output=out, stderr=err)


def run_mode(mode: str, args) -> dict:
    """Each mode runs in a subprocess: one TPU process at a time, and the
    force-accept env must not leak between modes."""
    env = dict(os.environ)
    env["INTELLILLM_BENCH_SIZE"] = "7b"
    env["INTELLILLM_BENCH_BS"] = str(args.bs)
    env["INTELLILLM_BENCH_OUT"] = str(args.out)
    env["INTELLILLM_BENCH_IN"] = str(args.input_len)
    if mode == "baseline":
        env["INTELLILLM_BENCH_K"] = str(args.k + 1)
    else:
        env["INTELLILLM_BENCH_SPEC"] = "1b"
        env["INTELLILLM_BENCH_SPEC_K"] = str(args.k)
        if mode == "ceiling":
            env["INTELLILLM_SPEC_FORCE_ACCEPT"] = "1"
        elif mode == "adaptive":
            # Full band + force-accept + a sub-second controller clock:
            # acceptance stays perfect so the controller grows K toward
            # k_max, crossing several ladder rungs during the run. The
            # mode's value vs ceiling shows what K transitions cost
            # (should be ~free: all rungs are boot-warmed).
            env["INTELLILLM_SPEC_FORCE_ACCEPT"] = "1"
            env["INTELLILLM_BENCH_SPEC_K_MIN"] = "1"
            env["INTELLILLM_BENCH_SPEC_K_MAX"] = str(args.k)
            env["INTELLILLM_SPEC_K_EVAL_S"] = "0.5"
            env["INTELLILLM_SPEC_K_GROW_PATIENCE"] = "2"
    t0 = time.time()
    try:
        rc, stdout, stderr = _run_bench_child(env, _MODE_TIMEOUT_S)
    except subprocess.TimeoutExpired as e:
        tail = (e.stderr or "").strip().splitlines()[-3:]
        return {"mode": mode, "rc": None,
                "wall_s": round(time.time() - t0, 1), "result": None,
                "error": f"timeout after {_MODE_TIMEOUT_S:.0f}s",
                "stderr_tail": tail}
    line = None
    for ln in stdout.strip().splitlines():
        try:
            line = json.loads(ln)
        except json.JSONDecodeError:
            continue
    return {"mode": mode, "rc": rc,
            "wall_s": round(time.time() - t0, 1), "result": line}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--k", type=int, default=4)
    ap.add_argument("--bs", type=int, default=32)
    ap.add_argument("--out", type=int, default=64)
    ap.add_argument("--input-len", type=int, default=128)
    ap.add_argument("--modes", default="baseline,floor,ceiling,adaptive")
    args = ap.parse_args()
    results = []
    for mode in args.modes.split(","):
        rec = run_mode(mode.strip(), args)
        print(json.dumps(rec), flush=True)
        results.append(rec)
    ok = [r for r in results if r["result"]]
    print(json.dumps({"spec_bench_summary": {
        r["mode"]: (r["result"] or {}).get("value") for r in results}}))
    return 0 if len(ok) == len(results) else 1


if __name__ == "__main__":
    sys.exit(main())
