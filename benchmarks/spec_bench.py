"""Speculative-decoding throughput bracketing on dummy weights.

Real acceptance rates need real checkpoints (random draft/target weights
never agree), so this harness brackets the machinery instead
(reference role: `vllm/worker/spec_decode/` — which the reference never
measured either, since it never wired the scaffold):

  - floor  (a~0):  7B target + 1B draft, real acceptance — every round
                   pays draft K+1 + verify K+1 and emits ~1 token
  - ceiling (a=1): same pair with INTELLILLM_SPEC_FORCE_ACCEPT=1 —
                   every round emits K+1 tokens
  - baseline:      plain 7B fused decode at the same K

Prints one JSON line per mode. Usage:
    python benchmarks/spec_bench.py [--k 4] [--bs 32] [--out 64]
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def run_mode(mode: str, args) -> dict:
    """Each mode runs in a subprocess: one TPU process at a time, and the
    force-accept env must not leak between modes."""
    env = dict(os.environ)
    env["INTELLILLM_BENCH_SIZE"] = "7b"
    env["INTELLILLM_BENCH_BS"] = str(args.bs)
    env["INTELLILLM_BENCH_OUT"] = str(args.out)
    env["INTELLILLM_BENCH_IN"] = str(args.input_len)
    if mode == "baseline":
        env["INTELLILLM_BENCH_K"] = str(args.k + 1)
    else:
        env["INTELLILLM_BENCH_SPEC"] = "1b"
        env["INTELLILLM_BENCH_SPEC_K"] = str(args.k)
        if mode == "ceiling":
            env["INTELLILLM_SPEC_FORCE_ACCEPT"] = "1"
    t0 = time.time()
    r = subprocess.run([sys.executable,
                        os.path.join(os.path.dirname(__file__), "..",
                                     "bench.py")],
                       capture_output=True, text=True, env=env,
                       timeout=2400)
    line = None
    for ln in r.stdout.strip().splitlines():
        try:
            line = json.loads(ln)
        except json.JSONDecodeError:
            continue
    return {"mode": mode, "rc": r.returncode,
            "wall_s": round(time.time() - t0, 1), "result": line}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--k", type=int, default=4)
    ap.add_argument("--bs", type=int, default=32)
    ap.add_argument("--out", type=int, default=64)
    ap.add_argument("--input-len", type=int, default=128)
    ap.add_argument("--modes", default="baseline,floor,ceiling")
    args = ap.parse_args()
    results = []
    for mode in args.modes.split(","):
        rec = run_mode(mode.strip(), args)
        print(json.dumps(rec), flush=True)
        results.append(rec)
    ok = [r for r in results if r["result"]]
    print(json.dumps({"spec_bench_summary": {
        r["mode"]: (r["result"] or {}).get("value") for r in results}}))
    return 0 if len(ok) == len(results) else 1


if __name__ == "__main__":
    sys.exit(main())
