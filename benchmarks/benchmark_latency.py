"""End-to-end latency of one fixed-size batch.

Role parity: reference `benchmarks/benchmark_latency.py` (same CLI
surface: --input-len/--output-len/--batch-size/--num-iters, profile
option). Runs a single `LLM.generate` over batch_size identical-length
prompts per iteration and reports the mean/percentile wall time.

Usage:
    python benchmarks/benchmark_latency.py --model dummy:7b \
        --input-len 32 --output-len 128 --batch-size 8
"""
from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from benchmarks.common import build_llm, is_dummy, percentiles  # noqa: E402


def main(args):
    from intellillm_tpu.sampling_params import SamplingParams

    llm = build_llm(args)
    engine = llm.llm_engine
    vocab = engine.model_config.get_vocab_size()
    rng = np.random.default_rng(args.seed)

    sampling_params = SamplingParams(
        n=args.n,
        temperature=0.0 if args.use_beam_search else 1.0,
        top_p=1.0,
        use_beam_search=args.use_beam_search,
        ignore_eos=True,
        max_tokens=args.output_len,
    )
    prompt_ids = [
        rng.integers(0, vocab, size=args.input_len).tolist()
        for _ in range(args.batch_size)
    ]

    def run():
        start = time.perf_counter()
        llm.generate(prompt_token_ids=prompt_ids,
                     sampling_params=sampling_params)
        return time.perf_counter() - start

    print("Warming up...")
    for _ in range(args.num_iters_warmup):
        run()

    if args.profile:
        import jax
        jax.profiler.start_trace(args.profile_result_dir)

    latencies = [run() for _ in range(args.num_iters)]

    if args.profile:
        import jax
        jax.profiler.stop_trace()
        print(f"Profile saved to {args.profile_result_dir}")

    stats = percentiles(latencies, (50, 90, 99))
    print(f"Avg latency: {np.mean(latencies):.4f} s")
    for k, v in stats.items():
        print(f"{k} latency: {v:.4f} s")
    tok_s = args.batch_size * args.output_len / np.mean(latencies)
    print(f"Throughput: {tok_s:.1f} output tok/s")


if __name__ == "__main__":
    from intellillm_tpu.utils import apply_platform_override
    apply_platform_override()
    parser = argparse.ArgumentParser(
        description="Benchmark the latency of processing a single batch "
        "of requests till completion.")
    parser.add_argument("--model", type=str, default="dummy:7b")
    parser.add_argument("--tokenizer", type=str, default=None)
    parser.add_argument("--quantization", "-q", type=str, default=None)
    parser.add_argument("--tensor-parallel-size", "-tp", type=int, default=1)
    parser.add_argument("--input-len", type=int, default=32)
    parser.add_argument("--output-len", type=int, default=128)
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--n", type=int, default=1)
    parser.add_argument("--use-beam-search", action="store_true")
    parser.add_argument("--num-iters-warmup", type=int, default=2)
    parser.add_argument("--num-iters", type=int, default=3)
    parser.add_argument("--dtype", type=str, default="auto")
    parser.add_argument("--max-model-len", type=int, default=None)
    parser.add_argument("--max-num-seqs", type=int, default=256)
    parser.add_argument("--num-device-blocks", type=int, default=None)
    parser.add_argument("--kv-cache-dtype", type=str, default="auto")
    parser.add_argument("--enforce-eager", action="store_true")
    parser.add_argument("--trust-remote-code", action="store_true")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--profile", action="store_true",
                        help="capture a jax.profiler trace around the "
                        "timed iterations")
    parser.add_argument("--profile-result-dir", type=str,
                        default="/tmp/intellillm-latency-profile")
    main(parser.parse_args())
