// Host-side batch-preparation kernels for the TPU engine.
//
// Role parity: the reference keeps its runtime hot paths native
// (csrc/ + the CUDA-graph-paired CPU batch prep in
// vllm/worker/model_runner.py:95-358 is the per-step host bottleneck its
// CUDA graphs exist to hide). On TPU the device step is one fused jit
// call, so the remaining per-step host work IS this: filling the padded
// (bucketed) batch arrays and computing KV slot mappings. These loops are
// O(batch * table_width) Python work per step; here they run as plain
// C++ over int32 buffers, called via ctypes (no pybind11 in the image).
//
// Build: g++ -O3 -shared -fPIC -o libbatch_prep.so batch_prep.cc
// (intellillm_tpu/native/__init__.py builds lazily and falls back to the
// pure-Python implementations if no compiler is available.)

#include <cstdint>
#include <cstring>

extern "C" {

// Fill the padded decode batch arrays from per-sequence values.
//   tables_flat/table_offsets: concatenated block tables (CSR-style),
//     offsets has n+1 entries.
//   out_* are preallocated [padded_n(, width)] arrays, zero-filled by the
//     caller for rows >= n.
void build_decode_batch(const int32_t* tables_flat,
                        const int64_t* table_offsets,
                        const int32_t* tokens,
                        const int32_t* positions,
                        const int32_t* ctx,
                        int64_t n,
                        int64_t width,
                        int32_t* out_tokens,
                        int32_t* out_positions,
                        int32_t* out_ctx,
                        int32_t* out_tables) {
  for (int64_t i = 0; i < n; ++i) {
    out_tokens[i] = tokens[i];
    out_positions[i] = positions[i];
    out_ctx[i] = ctx[i];
    const int64_t start = table_offsets[i];
    int64_t len = table_offsets[i + 1] - start;
    // Clamp: a table longer than the padded width must never write past
    // its row (the Python fallback raises here; heap corruption is worse).
    if (len > width) len = width;
    std::memcpy(out_tables + i * width, tables_flat + start,
                sizeof(int32_t) * static_cast<size_t>(len));
  }
}

// KV slot mapping for one prompt sequence (reference
// model_runner.py:157-179 incl. the sliding-window suppression at
// :160-170): slot for token t is table[t / block_size] * block_size +
// t % block_size; with a window, logical blocks wrap modulo
// window_blocks and tokens that would be overwritten within this same
// prefill emit pad_slot (scatter order is unspecified).
void build_prompt_slots(const int32_t* table,
                        int64_t prefix_len,
                        int64_t seq_len,
                        int64_t block_size,
                        int64_t window_blocks,  // 0 = no sliding window
                        int32_t pad_slot,
                        int32_t* out_slots) {
  int64_t k = 0;
  for (int64_t t = prefix_len; t < seq_len; ++t, ++k) {
    int64_t logical = t / block_size;
    if (window_blocks > 0) {
      if (t < seq_len - window_blocks * block_size) {
        out_slots[k] = pad_slot;
        continue;
      }
      logical %= window_blocks;
    }
    out_slots[k] = table[logical] * static_cast<int32_t>(block_size) +
                   static_cast<int32_t>(t % block_size);
  }
}

}  // extern "C"
